"""`make trace`: end-to-end trace-plane validation (docs/observability.md).

Replays the locked 6k churn prefix (seed 0, 2000 nodes — repo CLAUDE.md)
through the DEVICE-resident path with tracing fully enabled
(``KSIM_TRACE_OUT``) in the sanitized CPU environment (runnable under
any hardware condition, like ``make faults``), then validates:

- the behavior locks hold byte-identically with tracing on (2524/471);
- the emitted Chrome-trace JSON parses and contains a
  lower/dispatch/reconcile span for EVERY on-device segment plus a
  ``store.txn_commit`` event per committed segment;
- with a ``KSIM_FAULTS`` schedule armed (second, smaller run), the
  timeline carries the ``fault.fired`` and ``replay.fallback`` events
  the chaos evidence story depends on;
- two CONCURRENT tenant jobs (fourth run, the job plane —
  ksim_tpu/jobs) record job-tagged ``runner.step``/``replay.dispatch``
  spans into ISOLATED per-job trace rings (every record in a job's
  ring carries that job's id and no other's), with both jobs landing
  identical counts;
- a 2-worker FLEET (fifth run, the fleet observability plane —
  docs/observability.md "Fleet observability"): every worker's
  SIGTERM-published trace export merges into ONE Chrome trace with one
  process lane per worker, job-tagged records attributed to the
  owning worker's lane, and at least one complete
  submit→claim→run flow-event triple (``s``/``t``/``f``) per job.

The parent process is stdlib-only (the bench.py crash-containment
pattern: jax backend init can wedge on a dead chip, so anything that
must complete runs jax only in subprocesses)."""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD_TIMEOUT_S = 840

# The locked 6k prefix (repo CLAUDE.md; tests/test_behavior_locks.py).
LOCK = (2524, 471)


# ---------------------------------------------------------------------------
# Child payload (imports jax; only ever runs in a subprocess)
# ---------------------------------------------------------------------------


def _child_jobs(events: int, nodes: int, out_path: str) -> None:
    """Two concurrent tenant jobs of the same churn stream through the
    job plane; dumps each job's state, counts, and PRIVATE trace ring
    for the parent's attribution/isolation asserts."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import jax

    from ksim_tpu.jobs import JobManager
    from ksim_tpu.scenario import churn_scenario, spec_from_operations
    from ksim_tpu.util import enable_compilation_cache, raise_map_count_limit

    enable_compilation_cache()
    raise_map_count_limit()
    jax.config.update("jax_enable_x64", False)
    doc = {
        "spec": {
            "simulator": {
                "maxPodsPerPass": 1024,
                "podBucketMin": 128,
                "deviceReplay": True,
                "preemption": True,
            },
            "scenario": spec_from_operations(
                list(
                    churn_scenario(
                        0, n_nodes=nodes, n_events=events, ops_per_step=100
                    )
                )
            ),
        }
    }
    jm = JobManager(workers=2, queue_limit=4)
    jobs = [jm.submit(doc) for _ in range(2)]
    finished = jm.join(timeout=CHILD_TIMEOUT_S - 60)
    record = {"finished": finished, "jobs": []}
    for j in jobs:
        state, result, err = j.result_view()
        counts = None
        replay = {}
        if result:
            counts = [
                result["result"]["podsScheduled"],
                result["result"]["unschedulableAttempts"],
            ]
            replay = result.get("replay") or {}
        record["jobs"].append(
            {
                "id": j.id,
                "state": state,
                "error": err,
                "counts": counts,
                "device_round_trips": replay.get("device_round_trips", 0),
                "ring": [
                    {"name": r["name"], "ph": r["ph"], "args": r["args"]}
                    for r in j.trace.ring_records()
                ],
            }
        )
    jm.shutdown(timeout=5)
    with open(out_path, "w") as f:
        json.dump(record, f)


def _child_fleet_obs(out_path: str) -> None:
    """A 2-worker process fleet behind an in-process front door: submit
    two tiny jobs, SIGTERM the workers (their final telemetry publish
    lands each worker's merged trace export in ``obs/``), then merge
    every published trace with flow stitching for the parent's
    lane/attribution/flow asserts."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import signal
    import tempfile as tf
    import time

    from ksim_tpu import obs
    from ksim_tpu.jobs import JobManager
    from tests.helpers import make_node, make_pod

    jobs_dir = tf.mkdtemp(prefix="ksim_fleet_obs_")
    workers: dict = {}
    for wid in ("w1", "w2"):
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ksim_tpu.jobs",
                "--dir", jobs_dir, "--worker-id", wid, "--workers", "1",
            ],
            cwd=_REPO, stdout=subprocess.PIPE, text=True,
        )
        line = proc.stdout.readline()
        if line.strip() != f"READY {wid}":
            raise SystemExit(f"worker {wid} never came up: {line!r}")
        workers[wid] = proc
    jm = JobManager(
        workers=0, queue_limit=8, jobs_dir=jobs_dir,
        role="frontdoor", worker_id="fd", lease_s=30.0, poll_s=0.2,
    )
    spec = {
        "spec": {
            "scenario": {
                "operations": [
                    {
                        "step": 0,
                        "createOperation": {"object": make_node("n0", cpu="4")},
                    },
                    {
                        "step": 1,
                        "createOperation": {"object": make_pod("p0", cpu="100m")},
                    },
                ]
            }
        }
    }
    submitted = [jm.submit(spec) for _ in range(2)]
    deadline = time.time() + CHILD_TIMEOUT_S - 120
    states: dict = {}
    for job in submitted:
        while True:
            st = job.status()
            if st["state"] in ("succeeded", "failed"):
                break
            if time.time() > deadline:
                break
            time.sleep(0.1)
        states[job.id] = st
    pids = {wid: p.pid for wid, p in workers.items()}
    for p in workers.values():
        p.send_signal(signal.SIGTERM)
    for p in workers.values():
        p.wait(timeout=60)
    jm.shutdown()
    traces = obs.read_fleet_traces(jobs_dir)
    record = {
        "worker_pids": pids,
        "frontdoor_pid": os.getpid(),
        "published": sorted(traces),
        "jobs": {
            j.id: {
                "state": states[j.id]["state"],
                "owner": states[j.id]["owner"],
            }
            for j in submitted
        },
        "merged": obs.merge_chrome_traces(traces, flows=True),
    }
    with open(out_path, "w") as f:
        json.dump(record, f)


def _child(events: int, nodes: int, out_path: str, fleet: int = 0) -> None:
    # Scripts put THEIR directory (tools/) on sys.path, not the repo.
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import jax

    from ksim_tpu.obs import TRACE
    from ksim_tpu.scenario import ScenarioRunner, churn_scenario
    from ksim_tpu.util import enable_compilation_cache, raise_map_count_limit

    enable_compilation_cache()
    raise_map_count_limit()
    jax.config.update("jax_enable_x64", False)
    runner = ScenarioRunner(
        max_pods_per_pass=1024,
        pod_bucket_min=128,
        device_replay=True,
        preemption=True,
        fleet=fleet or None,
    )
    res = runner.run(
        churn_scenario(0, n_nodes=nodes, n_events=events, ops_per_step=100)
    )
    drv = runner.replay_driver
    # Flush the trace explicitly (the atexit hook would too; an explicit
    # write means the result JSON below can promise the file exists).
    if TRACE.out_path:
        TRACE.export_chrome(TRACE.out_path)
    record = {
        "scheduled": res.pods_scheduled,
        "unschedulable": res.unschedulable_attempts,
        "steps": len(res.steps),
        "phases": res.phase_seconds,
        **drv.stats(),
    }
    if fleet:
        record["lane_counts"] = [
            [r.pods_scheduled, r.unschedulable_attempts] for r in res.lanes
        ]
        record["fleet"] = runner.fleet_driver.stats()
    with open(out_path, "w") as f:
        json.dump(record, f)


# ---------------------------------------------------------------------------
# Parent validation (stdlib only)
# ---------------------------------------------------------------------------


def _sanitized_env() -> dict:
    sys.path.insert(0, _REPO)
    try:
        from tests.helpers import sanitized_cpu_env
    finally:
        sys.path.pop(0)
    return sanitized_cpu_env()


def _run_child(
    events: int, nodes: int, env: dict, tmp: str, tag: str, fleet: int = 0
) -> tuple[dict, dict]:
    """One traced child replay; returns (result record, trace doc)."""
    trace_path = os.path.join(tmp, f"trace_{tag}.json")
    result_path = os.path.join(tmp, f"result_{tag}.json")
    env = dict(env, KSIM_TRACE_OUT=trace_path)
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", "--events", str(events), "--nodes", str(nodes),
        "--out", result_path, "--fleet", str(fleet),
    ]
    proc = subprocess.run(cmd, cwd=_REPO, env=env, timeout=CHILD_TIMEOUT_S)
    if proc.returncode != 0:
        raise SystemExit(f"trace-check child ({tag}) exited rc={proc.returncode}")
    with open(result_path) as f:
        result = json.load(f)
    with open(trace_path) as f:
        trace = json.load(f)  # must PARSE — that is half the check
    return result, trace


def _span_counts(trace: dict) -> dict[str, int]:
    out: dict[str, int] = {}
    for ev in trace.get("traceEvents", ()):
        if ev.get("ph") in ("X", "i"):
            out[ev["name"]] = out.get(ev["name"], 0) + 1
    return out


def _fail(msg: str) -> None:
    raise SystemExit(f"trace-check FAILED: {msg}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--child-jobs", action="store_true")
    ap.add_argument("--child-fleet-obs", action="store_true")
    ap.add_argument("--events", type=int, default=6000)
    ap.add_argument("--nodes", type=int, default=2000)
    ap.add_argument("--out", type=str, default="")
    ap.add_argument("--fleet", type=int, default=0)
    args = ap.parse_args()
    if args.child_fleet_obs:
        _child_fleet_obs(args.out)
        return
    if args.child_jobs:
        _child_jobs(args.events, args.nodes, args.out)
        return
    if args.child:
        _child(args.events, args.nodes, args.out, args.fleet)
        return

    env = _sanitized_env()
    with tempfile.TemporaryDirectory(prefix="ksim_trace_check_") as tmp:
        # -- run 1: the locked 6k prefix, fully traced, no faults ------
        result, trace = _run_child(args.events, args.nodes, env, tmp, "clean")
        counts = (result["scheduled"], result["unschedulable"])
        if args.events == 6000 and args.nodes == 2000 and counts != LOCK:
            _fail(f"locked counts diverged under tracing: {counts} != {LOCK}")
        names = _span_counts(trace)
        segments = result["device_round_trips"]
        if segments < 1:
            _fail(f"no device segments ran (stats: {result})")
        for span in ("replay.lower", "replay.dispatch"):
            if names.get(span, 0) < segments:
                _fail(
                    f"{span}: {names.get(span, 0)} spans for {segments} "
                    f"dispatched segments"
                )
        # The double-buffered executor (round 10) pre-lowers every
        # non-final window's successor while its dispatch is in flight.
        if segments > 1 and not names.get("replay.prelower"):
            _fail("pipelined run recorded no replay.prelower spans")
        cache = result.get("lower_cache", {})
        if segments > 1 and not cache.get("hits"):
            _fail(f"lowered-universe cache never hit across {segments} segments: {cache}")
        # device_round_trips counts HEALTHY dispatches only (errored
        # ones never increment it); of those, post-dispatch validation
        # discards return before any reconcile, and a reconcile that
        # rolled back has a span but no commit.
        unsupported = result.get("unsupported", {})
        discards = unsupported.get("featurize_prediction", 0) + unsupported.get(
            "preemption_overflow", 0
        )
        reconciled = segments - discards
        committed = reconciled - unsupported.get("reconcile_fault", 0)
        if names.get("replay.reconcile", 0) < reconciled:
            _fail(
                f"replay.reconcile: {names.get('replay.reconcile', 0)} spans "
                f"for {reconciled} reconciled segments"
            )
        if names.get("store.txn_commit", 0) < committed:
            _fail(
                f"store.txn_commit: {names.get('store.txn_commit', 0)} events "
                f"for {committed} committed segments"
            )
        if result["fallback_steps"] and not names.get("runner.step"):
            _fail("fallback steps ran but no runner.step spans recorded")
        print(
            f"trace-check: clean run OK — counts {counts}, "
            f"{segments} segments, spans {({k: names[k] for k in sorted(names)})}"
        )

        # -- run 2: a KSIM_FAULTS schedule armed -----------------------
        # One injected dispatch failure over a small prefix: the
        # timeline must show the fault firing AND the resulting
        # degradation (device_error fallback -> per-pass step).
        armed_env = dict(env, KSIM_FAULTS="replay.dispatch=call:1")
        result2, trace2 = _run_child(1000, 500, armed_env, tmp, "armed")
        names2 = _span_counts(trace2)
        if not names2.get("fault.fired"):
            _fail("armed run recorded no fault.fired event")
        if not names2.get("replay.fallback"):
            _fail("armed run recorded no replay.fallback event")
        reasons = {
            ev["args"].get("reason")
            for ev in trace2["traceEvents"]
            if ev.get("name") == "replay.fallback"
        }
        if "device_error" not in reasons:
            _fail(f"armed run's fallback reasons lack device_error: {reasons}")
        print(
            f"trace-check: armed run OK — fault.fired x{names2['fault.fired']}, "
            f"fallback reasons {sorted(r for r in reasons if r)}"
        )

        # -- run 3: a 2-lane FLEET replay (round 12) -------------------
        # Per-lane span attribution: every replay.dispatch span of a
        # fleet run must name the lanes it advanced, and every
        # replay.reconcile span the ONE lane it reconciled — a Chrome
        # trace from an S-lane run is useless if the phases are not
        # attributable per trajectory.
        result3, trace3 = _run_child(1000, 500, env, tmp, "fleet", fleet=2)
        fleet_stats = result3.get("fleet", {})
        if fleet_stats.get("group_dispatches", 0) < 1:
            _fail(f"fleet run dispatched no groups (stats: {fleet_stats})")
        if any(c != result3["lane_counts"][0] for c in result3["lane_counts"]):
            _fail(f"fleet lanes diverged: {result3['lane_counts']}")
        dispatch_spans = [
            ev
            for ev in trace3["traceEvents"]
            if ev.get("name") == "replay.dispatch" and ev.get("ph") == "X"
        ]
        reconcile_spans = [
            ev
            for ev in trace3["traceEvents"]
            if ev.get("name") == "replay.reconcile" and ev.get("ph") == "X"
        ]
        if not dispatch_spans or not reconcile_spans:
            _fail("fleet run recorded no dispatch/reconcile spans")
        for ev in dispatch_spans:
            if "lane" not in ev.get("args", {}):
                _fail(f"fleet replay.dispatch span without lane attribution: {ev}")
        lanes_seen = set()
        for ev in reconcile_spans:
            lane = ev.get("args", {}).get("lane")
            if lane is None:
                _fail(f"fleet replay.reconcile span without lane attribution: {ev}")
            lanes_seen.add(lane)
        if lanes_seen != {0, 1}:
            _fail(f"fleet reconcile spans cover lanes {lanes_seen}, expected {{0, 1}}")
        print(
            f"trace-check: fleet run OK — {fleet_stats['group_dispatches']} group "
            f"dispatches, reconcile lanes {sorted(lanes_seen)}"
        )

        # -- run 4: two CONCURRENT tenant jobs (the job plane) ---------
        # Per-job isolation made checkable: every record in a job's
        # private ring must carry that job's id (the scoped trace
        # plane's tag), the two rings must never cross-contaminate,
        # and the locked stream must land the same counts in both.
        result4_path = os.path.join(tmp, "result_jobs.json")
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--child-jobs", "--events", "1000", "--nodes", "500",
            "--out", result4_path,
        ]
        proc = subprocess.run(cmd, cwd=_REPO, env=env, timeout=CHILD_TIMEOUT_S)
        if proc.returncode != 0:
            raise SystemExit(f"trace-check child (jobs) exited rc={proc.returncode}")
        with open(result4_path) as f:
            result4 = json.load(f)
        if not result4.get("finished"):
            _fail(f"job-plane run did not finish: {result4}")
        ids = [j["id"] for j in result4["jobs"]]
        if len(set(ids)) != 2:
            _fail(f"expected 2 distinct jobs, got {ids}")
        counts_seen = []
        for jrec in result4["jobs"]:
            if jrec["state"] != "succeeded":
                _fail(f"job {jrec['id']} ended {jrec['state']}: {jrec['error']}")
            counts_seen.append(jrec["counts"])
            if jrec["device_round_trips"] < 1:
                _fail(f"job {jrec['id']} ran no device segments")
            names4 = {}
            for rec in jrec["ring"]:
                names4[rec["name"]] = names4.get(rec["name"], 0) + 1
                tag = rec["args"].get("job")
                if tag != jrec["id"]:
                    _fail(
                        f"record in {jrec['id']}'s ring tagged job={tag!r} "
                        f"({rec['name']}) — per-job rings must be isolated"
                    )
            for span in ("jobs.run", "replay.dispatch"):
                if not names4.get(span):
                    _fail(f"job {jrec['id']}'s ring has no {span} span")
            if not names4.get("runner.step") and not names4.get("replay.reconcile"):
                _fail(f"job {jrec['id']}'s ring has no step/reconcile spans")
        if counts_seen[0] != counts_seen[1]:
            _fail(f"concurrent jobs diverged: {counts_seen}")
        print(
            f"trace-check: jobs run OK — 2 isolated job rings, counts "
            f"{counts_seen[0]}"
        )

        # -- run 5: a 2-worker fleet obs leg (round 19) ----------------
        # The fleet observability plane end-to-end: two worker
        # PROCESSES publish their trace exports at SIGTERM, the merged
        # Chrome trace must carry one process lane per worker, every
        # job-tagged run record must sit in its owning worker's lane,
        # and each job must draw a complete submit->claim->run flow
        # arrow (s/t/f triple) across the lanes.
        result5_path = os.path.join(tmp, "result_fleet_obs.json")
        fleet_env = dict(
            env,
            KSIM_TRACE="1",
            KSIM_OBS_PUBLISH_S="5",
            KSIM_WORKERS_POLL_S="0.2",
            KSIM_WORKERS_LEASE_S="30",
        )
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--child-fleet-obs", "--out", result5_path,
        ]
        proc = subprocess.run(
            cmd, cwd=_REPO, env=fleet_env, timeout=CHILD_TIMEOUT_S
        )
        if proc.returncode != 0:
            raise SystemExit(
                f"trace-check child (fleet-obs) exited rc={proc.returncode}"
            )
        with open(result5_path) as f:
            result5 = json.load(f)
        worker_pids = result5["worker_pids"]  # wid -> pid
        jobs5 = result5["jobs"]  # jid -> {state, owner}
        for jid, jrec in jobs5.items():
            if jrec["state"] != "succeeded":
                _fail(f"fleet-obs job {jid} ended {jrec['state']}")
            if jrec["owner"] not in worker_pids:
                _fail(
                    f"fleet-obs job {jid} owned by {jrec['owner']!r}, "
                    f"not a fleet worker {sorted(worker_pids)}"
                )
        missing = set(worker_pids) - set(result5["published"])
        if missing:
            _fail(f"workers never published a trace export: {sorted(missing)}")
        merged5 = result5["merged"]["traceEvents"]
        # One process lane per worker: exactly one process_name
        # metadata record per worker id, all on distinct pids.
        lanes = {}
        for ev in merged5:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                lanes.setdefault(ev["args"]["name"], set()).add(ev["pid"])
        for wid, pid in worker_pids.items():
            if lanes.get(wid) != {pid}:
                _fail(
                    f"worker {wid} lane is {sorted(lanes.get(wid, ()))}, "
                    f"expected exactly its pid {pid}"
                )
        # Job-tagged run records attribute to the OWNING worker's lane.
        runs_seen = set()
        for ev in merged5:
            if ev.get("name") != "jobs.run" or ev.get("ph") != "X":
                continue
            jid = (ev.get("args") or {}).get("job")
            if jid not in jobs5:
                continue
            owner_pid = worker_pids[jobs5[jid]["owner"]]
            if ev.get("pid") != owner_pid:
                _fail(
                    f"job {jid} run record in pid {ev.get('pid')}'s lane; "
                    f"owner {jobs5[jid]['owner']} is pid {owner_pid}"
                )
            runs_seen.add(jid)
        if runs_seen != set(jobs5):
            _fail(
                f"merged trace lacks jobs.run spans for "
                f"{sorted(set(jobs5) - runs_seen)}"
            )
        # >=1 COMPLETE submit->claim->run flow triple per job.
        flows: dict = {}
        for ev in merged5:
            if ev.get("name") == "jobs.flow":
                flows.setdefault(ev["args"]["job"], set()).add(ev["ph"])
        for jid in jobs5:
            if flows.get(jid) != {"s", "t", "f"}:
                _fail(
                    f"job {jid} flow phases {sorted(flows.get(jid, ()))}, "
                    f"expected a complete s/t/f triple"
                )
        print(
            f"trace-check: fleet-obs run OK — lanes {sorted(lanes)}, "
            f"{len(flows)} complete flow triples"
        )
    print("trace-check: PASS")


if __name__ == "__main__":
    main()
