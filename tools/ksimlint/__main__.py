"""CLI: ``python -m tools.ksimlint [targets...]`` (see docs/lint.md).

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage
error.  ``--format json`` emits one machine-readable document (all
findings, suppressed included); ``--format sarif`` emits SARIF 2.1.0
for code-scanning UIs (suppressed findings carry an in-source
suppression object, so the upload stays in sync with the inline audit
trail).  The human format prints ``path:line: [rule] message``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.ksimlint.core import DEFAULT_TARGETS, run
from tools.ksimlint.rules import RULE_DOCS


def _sarif(findings) -> dict:
    """Minimal schema-valid SARIF 2.1.0: one run, one result per
    finding (suppressed ones carry ``suppressions``), rule metadata
    from each plugin's docstring."""
    rule_ids = sorted(RULE_DOCS)
    index = {r: i for i, r in enumerate(rule_ids)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": f.line},
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {"kind": "inSource", "justification": "ksimlint: disable"}
            ]
        results.append(result)
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "ksimlint",
                        "informationUri": "docs/lint.md",
                        "rules": [
                            {
                                "id": r,
                                "shortDescription": {"text": RULE_DOCS[r]},
                            }
                            for r in rule_ids
                        ],
                    }
                },
                "results": results,
            }
        ],
    }


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="ksimlint", description="AST contract analyzer (docs/lint.md)"
    )
    parser.add_argument(
        "targets",
        nargs="*",
        help=f"files/directories under --root (default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repository root (default: derived from this file's location)",
    )
    parser.add_argument(
        "--rules", help="comma-separated rule subset (default: all rules)"
    )
    parser.add_argument(
        "--rule",
        action="append",
        default=[],
        metavar="RULE",
        help="run one rule (repeatable; combines with --rules)",
    )
    parser.add_argument(
        "--format",
        choices=("human", "json", "sarif"),
        default="human",
        help="output format (default: human lines)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="alias for --format json",
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings in the human format",
    )
    args = parser.parse_args(argv)

    targets = tuple(args.targets) or DEFAULT_TARGETS
    selected = list(args.rule)
    if args.rules:
        selected.extend(r for r in args.rules.split(",") if r)
    rules = tuple(selected) if selected else None
    fmt = "json" if args.json else args.format
    try:
        findings = run(args.root, targets, rules)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"ksimlint: {e}", file=sys.stderr)
        return 2

    open_findings = [f for f in findings if not f.suppressed]
    suppressed = len(findings) - len(open_findings)
    if fmt == "json":
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "unsuppressed": len(open_findings),
                    "suppressed": suppressed,
                },
                indent=2,
            )
        )
    elif fmt == "sarif":
        print(json.dumps(_sarif(findings), indent=2))
    else:
        shown = findings if args.show_suppressed else open_findings
        for f in shown:
            print(f.format())
        print(
            f"ksimlint: {len(open_findings)} finding(s), {suppressed} suppressed",
            file=sys.stderr,
        )
    return 1 if open_findings else 0


if __name__ == "__main__":
    sys.exit(main())
