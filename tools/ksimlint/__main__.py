"""CLI: ``python -m tools.ksimlint [targets...]`` (see docs/lint.md).

Exit status: 0 = no unsuppressed findings, 1 = findings, 2 = usage
error.  ``--json`` emits one machine-readable document (all findings,
suppressed included) for tooling; the human format prints unsuppressed
findings as ``path:line: [rule] message``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.ksimlint.core import DEFAULT_TARGETS, run


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="ksimlint", description="AST contract analyzer (docs/lint.md)"
    )
    parser.add_argument(
        "targets",
        nargs="*",
        help=f"files/directories under --root (default: {' '.join(DEFAULT_TARGETS)})",
    )
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        help="repository root (default: derived from this file's location)",
    )
    parser.add_argument(
        "--rules", help="comma-separated rule subset (default: all rules)"
    )
    parser.add_argument(
        "--json", action="store_true", help="emit one JSON document instead of lines"
    )
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings in the human format",
    )
    args = parser.parse_args(argv)

    targets = tuple(args.targets) or DEFAULT_TARGETS
    rules = tuple(r for r in args.rules.split(",") if r) if args.rules else None
    try:
        findings = run(args.root, targets, rules)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"ksimlint: {e}", file=sys.stderr)
        return 2

    open_findings = [f for f in findings if not f.suppressed]
    suppressed = len(findings) - len(open_findings)
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_json() for f in findings],
                    "unsuppressed": len(open_findings),
                    "suppressed": suppressed,
                },
                indent=2,
            )
        )
    else:
        shown = findings if args.show_suppressed else open_findings
        for f in shown:
            print(f.format())
        print(
            f"ksimlint: {len(open_findings)} finding(s), {suppressed} suppressed",
            file=sys.stderr,
        )
    return 1 if open_findings else 0


if __name__ == "__main__":
    sys.exit(main())
