"""lock-order: the global lock-acquisition-order graph stays acyclic
and every cross-lock nesting is DECLARED.

The interprocedural deadlock tier (docs/lint.md "Lock order"; Naik et
al., "Effective static deadlock detection", ICSE 2009): the shared call
graph (tools/ksimlint/callgraph.py) gives every function its lexically
held lock-domain set at each acquisition and call site; an edge
``A -> B`` exists wherever ``B`` is acquired — directly, or anywhere in
a callee's transitive may-acquire set — while ``A`` is held.

Findings:

- **Undeclared nesting.** An observed edge not covered by a
  ``# ksimlint: lock-order(A<B)`` declaration (chains ``A<B<C`` expand
  to adjacent pairs; declarations live next to the docstring that
  justifies the order).  One finding per EDGE, reported at its first
  witness site.
- **Cycle.** Any cycle in observed-union-blessed edges — two blessed
  edges ``A<B`` and ``B<A`` are exactly a declared deadlock.  An edge
  whose EVERY witness site carries ``# ksimlint: disable=lock-order``
  is *waived* — excluded from the cycle graph.  That is the escape
  hatch for inversions that are unreachable by construction (the
  JobManager ``_recover`` path runs before any worker thread exists);
  the per-site suppressions still count in the audited suppression
  total, so a waiver is never silent.
- **Reentrant self-deadlock.** Directly re-acquiring a held non-RLock
  domain (``with self._lock:`` nested inside itself) — guaranteed
  deadlock, no cycle needed.
- **Dead declaration.** A blessed edge neither end of which is ever
  observed (full-tree runs only) — stale declarations would quietly
  bless future regressions.

Lock domains are ``ClassName.attr`` / ``modulestem.NAME`` where a
``threading.Lock/RLock/Condition`` is constructed.  Soundness limits
(dynamic dispatch, ``getattr``, properties, locks handed through
untyped receivers) are documented in docs/lint.md — a missed edge is
possible, an invented one is not.
"""

from __future__ import annotations

from tools.ksimlint.core import Finding, Project

RULE = "lock-order"


def _cycles(edges: set) -> list:
    """Elementary cycles via DFS over the domain graph; each cycle is
    reported once, rotated to its lexicographically smallest node."""
    graph: dict[str, list] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
        graph.setdefault(b, [])
    for outs in graph.values():
        outs.sort()

    seen_cycles = set()
    cycles = []

    def dfs(start: str, node: str, path: list, on_path: set) -> None:
        for nxt in graph[node]:
            if nxt == start:
                cyc = path[:]
                pivot = min(range(len(cyc)), key=lambda i: cyc[i])
                canon = tuple(cyc[pivot:] + cyc[:pivot])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(canon)
            elif nxt not in on_path and nxt > start:
                # Only explore nodes > start: each cycle is found from
                # its smallest node exactly once.
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def check(project: Project) -> list[Finding]:
    graph = project.callgraph()
    findings: list[Finding] = []
    observed = graph.observed_edges()
    blessed = graph.blessed_edges

    def _suppressed_at(rel: str, line: int) -> bool:
        sf = project.files.get(rel)
        if sf is None:
            return False
        return bool({RULE, "all"} & sf.disabled_at(line))

    # -- undeclared nestings --------------------------------------------
    waived = set()
    for edge in sorted(observed):
        witnesses = observed[edge]
        open_witnesses = [
            w for w in witnesses if not _suppressed_at(w[0], w[1])
        ]
        if not open_witnesses:
            # Every witness individually suppressed: the edge is waived
            # out of the cycle graph below.
            waived.add(edge)
        if edge in blessed:
            continue
        a, b = edge
        # Report at the first OPEN witness so a suppression on witness
        # one cannot shadow an unsuppressed witness two; a fully waived
        # edge reports (suppressed) at its first site for the audit pin.
        rel, line, desc = (open_witnesses or witnesses)[0]
        more = (
            f" (+{len(witnesses) - 1} more site(s))" if len(witnesses) > 1 else ""
        )
        findings.append(
            Finding(
                RULE,
                rel,
                line,
                f"undeclared lock nesting {a} -> {b}: {desc}{more} — declare "
                f"`# ksimlint: lock-order({a}<{b})` beside the docstring "
                "that justifies the order, or restructure to drop the "
                "first lock",
            )
        )

    # -- cycles ----------------------------------------------------------
    all_edges = (set(observed) - waived) | set(blessed)
    for cyc in _cycles(all_edges):
        ring = " -> ".join(cyc + (cyc[0],))
        # Anchor the finding on a concrete edge of the cycle: the first
        # observed witness if any, else the first blessed declaration.
        anchor = None
        for a, b in zip(cyc, cyc[1:] + (cyc[0],)):
            ws = observed.get((a, b))
            if ws:
                anchor = (ws[0][0], ws[0][1])
                break
        if anchor is None:
            for a, b in zip(cyc, cyc[1:] + (cyc[0],)):
                if (a, b) in blessed:
                    anchor = blessed[(a, b)]
                    break
        rel, line = anchor
        findings.append(
            Finding(
                RULE,
                rel,
                line,
                f"lock-order cycle {ring}: two threads taking these locks "
                "in opposite orders deadlock — break the cycle or drop "
                "the offending lock-order declaration",
            )
        )

    # -- reentrant self-deadlocks ---------------------------------------
    for fi, acq in graph.reentrant_acquisitions():
        findings.append(
            Finding(
                RULE,
                fi.rel,
                acq.line,
                f"{fi.display()} re-acquires non-reentrant {acq.domain} "
                "while already holding it — guaranteed self-deadlock "
                "(use the _locked helper convention or an RLock)",
            )
        )

    # -- dead declarations (full tree only) ------------------------------
    if project.covers_default_targets():
        for edge in sorted(blessed):
            if edge not in observed:
                rel, line = blessed[edge]
                findings.append(
                    Finding(
                        RULE,
                        rel,
                        line,
                        f"lock-order({edge[0]}<{edge[1]}) is declared but "
                        "never observed — stale declarations quietly bless "
                        "future regressions; delete it or fix the analyzer "
                        "blind spot it was covering",
                    )
                )
    return findings
