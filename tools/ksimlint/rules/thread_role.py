"""thread-role: thread entry points carry roles, and worker contracts
hold for everything REACHABLE from a worker.

Round 8 pinned the dispatch-worker contract on two hand-annotated
functions; this rule makes the property interprocedural (docs/lint.md
"Thread roles"):

- **Entry points carry roles.** Every ``threading.Thread(target=X)`` /
  ``pool.submit(X, ...)`` whose target resolves to a project function
  must find a ``# ksimlint: thread-role(<role>)`` annotation on that
  def (legacy ``# ksimlint: worker-thread`` = ``dispatch-worker``).
  Targets that resolve OUTSIDE the project (``serve_forever``) are
  skipped — the conservative-dispatch soundness limit.
- **Role vocabulary** (docs/lint.md): ``main-thread``,
  ``dispatch-worker``, ``job-worker``, ``sse-handler``, ``compactor``,
  ``service-loop``, ``fleet-poller``, ``obs-publisher``,
  ``trace-ingest``.  Anything
  else is a finding (a
  typo'd role would silently opt out of every check below).
- **Dispatch-worker strictness, propagated.**  The round-8 "no store to
  self" contract applies to every function reachable from a
  ``dispatch-worker`` root along same-receiver (``self.m()`` / nested
  def / same-module call) edges — an abandoned watchdog worker must not
  corrupt the degraded run's accounting through a helper either.
- **Cross-thread writes, propagated.**  Functions reachable from ANY
  non-main role root must not WRITE attributes annotated
  ``# guarded-by: main-thread`` (reads tolerate tearing — evidence
  snapshots rely on that).
- **Confinement assertions.**  A function annotated
  ``thread-role(main-thread)`` reachable from a worker root is a
  finding — the annotation is a machine-checked "never on a worker".

Reachability is same-receiver only: cross-object calls are covered by
the callee's own guarded-by discipline (lock-discipline rule), and
following them through untyped receivers would need the dynamic
dispatch the analyzer deliberately refuses to guess at.  ``__init__``
stores are exempt everywhere: a constructor reached from a worker is
initializing the FRESH instance being built (``ClassName(...)`` always
allocates), not shared state — the RacerD ownership rule.
"""

from __future__ import annotations

import ast

from tools.ksimlint.core import Finding, Project
from tools.ksimlint.rules.lock_discipline import MAIN_THREAD, _class_guards

RULE = "thread-role"

ROLES = frozenset(
    {
        "main-thread",
        "dispatch-worker",
        "job-worker",
        "sse-handler",
        "compactor",
        "service-loop",
        "fleet-poller",
        "obs-publisher",
        "trace-ingest",
    }
)

#: Roles whose reachable set must not store to self AT ALL (round 8).
STRICT_NO_STORE = frozenset({"dispatch-worker"})
#: Roles that run off the main thread (main-thread-guarded writes ban).
OFF_MAIN = ROLES - {"main-thread"}

_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)


def _self_stores(fn) -> list:
    """(attr, line) for every self.<attr> Store/Del/AugAssign in ``fn``
    EXCLUDING nested defs (those are separate graph nodes)."""
    out = []
    skip: set[int] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, _FUNC) and sub is not fn:
            for inner in ast.walk(sub):
                skip.add(id(inner))
    for sub in ast.walk(fn):
        if id(sub) in skip:
            continue
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
            and isinstance(sub.ctx, (ast.Store, ast.Del))
        ):
            out.append((sub.attr, sub.lineno))
    return out


def check(project: Project) -> list[Finding]:
    graph = project.callgraph()
    findings: list[Finding] = []

    # -- role annotations are well-formed -------------------------------
    for fi in graph.functions.values():
        if fi.role is not None and fi.role not in ROLES:
            findings.append(
                Finding(
                    RULE,
                    fi.rel,
                    fi.node.lineno,
                    f"unknown thread-role {fi.role!r} on {fi.display()} "
                    f"(vocabulary: {', '.join(sorted(ROLES))})",
                )
            )

    # -- every resolved Thread/submit target carries a role -------------
    for site in sorted(graph.thread_sites, key=lambda s: (s.rel, s.line)):
        if site.target is None:
            continue  # external / unresolvable target: soundness limit
        fi = graph.functions[site.target]
        if fi.role is None:
            findings.append(
                Finding(
                    RULE,
                    site.rel,
                    site.line,
                    f"thread target {site.expr} ({fi.display()}) has no "
                    "role annotation — add `# ksimlint: thread-role(...)` "
                    "on its def line (docs/lint.md \"Thread roles\")",
                )
            )

    # -- propagation ------------------------------------------------------
    strict_roots = graph.roots_with_role(STRICT_NO_STORE)
    worker_roots = graph.roots_with_role(OFF_MAIN)
    strict_reach = graph.reachable_same_receiver(strict_roots)
    worker_reach = graph.reachable_same_receiver(worker_roots)

    def via(key: str, reach: dict) -> str:
        root, through = reach[key]
        fi = graph.functions[key]
        if root.key == key:
            return f"{fi.display()} is a {root.role} root"
        return (
            f"{fi.display()} is reachable from {root.role} root "
            f"{root.display()} (via {through.display()})"
        )

    # Dispatch-worker strictness: no self stores anywhere reachable.
    for key in sorted(strict_reach):
        fi = graph.functions[key]
        if fi.name == "__init__":
            continue  # constructor: self is the fresh instance (ownership)
        for attr, line in _self_stores(fi.node):
            findings.append(
                Finding(
                    RULE,
                    fi.rel,
                    line,
                    f"store to self.{attr} in dispatch-worker-reachable "
                    f"code: {via(key, strict_reach)} — dispatch workers "
                    "must be side-effect-free on the instance (apply "
                    "state on the main thread after join)",
                )
            )

    # Off-main reachability: no writes to main-thread-guarded attrs, and
    # no reaching a function pinned main-thread.
    for key in sorted(worker_reach):
        fi = graph.functions[key]
        if fi.role == "main-thread" and worker_reach[key][0].key != key:
            findings.append(
                Finding(
                    RULE,
                    fi.rel,
                    fi.node.lineno,
                    f"main-thread-pinned function violated: "
                    f"{via(key, worker_reach)}",
                )
            )
            continue
        if fi.cls is None or key in strict_reach or fi.name == "__init__":
            continue  # strict check above already covers every store
        guards = _class_guards(fi.sf, fi.cls.node)
        for attr, line in _self_stores(fi.node):
            if guards.get(attr) == MAIN_THREAD:
                findings.append(
                    Finding(
                        RULE,
                        fi.rel,
                        line,
                        f"write to main-thread-confined self.{attr}: "
                        f"{via(key, worker_reach)} — main-thread state "
                        "may only be read off-main (snapshot tearing is "
                        "tolerated, cross-thread writes are not)",
                    )
                )
    return findings
