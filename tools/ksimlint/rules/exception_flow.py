"""exception-flow: cancellation, fault and fallback exceptions travel
only their documented channels.

Three contracts from rounds 13-16, made machine-checked (docs/lint.md
"Exception flow"):

- **RunCancelled must not be absorbed.**  ``errors.RunCancelled`` is
  deliberately NOT a SimulatorError: classified fault handlers absorb
  SimulatorErrors into per-pass fallbacks, and a cancellation must
  propagate out of the run.  A broad handler (``except Exception`` /
  ``except BaseException`` / bare) whose try body may raise RunCancelled
  — computed interprocedurally over the call graph — must re-raise it:
  an earlier ``except RunCancelled`` arm, a bare ``raise``, re-raising
  or CAPTURING the bound exception (``box["err"] = e``, the watchdog
  worker's classified-by-the-caller pattern), or an isinstance re-raise
  all count.
- **InjectedFault containment matches the taxonomy.**  Explicitly
  catching ``InjectedFault`` is the privilege of the documented
  containment scopes (docs/faults.md): the segment-reconcile rollback
  in ``scenario/runner.py``.  Anywhere else, chaos must flow through
  the classified SimulatorError ladders, not be picked off by name.
- **ReplayFallback rides its constructors.**  ``raise
  ReplayFallback(...)`` appears nowhere: fallbacks are raised as
  ``_Unsupported(<reason>)`` (whose static reasons registry-literals
  pins to FALLBACK_REASONS) or recorded via ``_reject`` — a direct
  raise would mint an unregistered reason the histogram cannot bucket.
"""

from __future__ import annotations

import ast

from tools.ksimlint.core import Finding, Project

RULE = "exception-flow"

#: Modules whose functions may explicitly catch InjectedFault — the
#: documented containment scopes (docs/faults.md "containment"): the
#: all-or-nothing segment-reconcile rollback.
INJECTED_FAULT_SCOPES = ("ksim_tpu/scenario/runner.py",)

#: Defs allowed to raise ReplayFallback directly (the constructors).
FALLBACK_RAISERS = ("_Unsupported", "_reject")

_BROAD = {"Exception", "BaseException"}
_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)


def _name_tail(expr) -> "str | None":
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _handler_types(handler: ast.ExceptHandler) -> set:
    if handler.type is None:
        return {"*bare*"}
    if isinstance(handler.type, ast.Tuple):
        return {_name_tail(e) or "?" for e in handler.type.elts}
    return {_name_tail(handler.type) or "?"}


def _reraises(handler: ast.ExceptHandler) -> bool:
    """The handler re-raises or captures-for-the-caller: a bare
    ``raise``, ``raise e`` of the bound name, or ANY use of the bound
    name beyond logging-free absorption (storing it into a box the
    caller classifies, wrapping it with ``raise X(...) from e`` —
    conservative: a bound name that flows anywhere counts)."""
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Raise):
            if sub.exc is None:
                return True
            if (
                handler.name
                and isinstance(sub.exc, ast.Name)
                and sub.exc.id == handler.name
            ):
                return True
    if handler.name:
        # Capture pattern: the bound exception assigned/stored somewhere
        # (box["err"] = e) — the caller owns classification, including
        # the RunCancelled re-raise.
        for sub in ast.walk(handler):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                value = getattr(sub, "value", None)
                if isinstance(value, ast.Name) and value.id == handler.name:
                    return True
    return False


def check(project: Project) -> list[Finding]:
    graph = project.callgraph()
    findings: list[Finding] = []
    may_cancel = graph.may_raise("RunCancelled")

    for fi in graph.functions.values():
        tries = [sub for sub in ast.walk(fi.node) if isinstance(sub, ast.Try)]
        if not tries:
            continue
        call_sites = graph.calls.get(fi.key, ())
        raise_sites = graph.raises.get(fi.key, ())
        for t in tries:
            broad = [
                h
                for h in t.handlers
                if _handler_types(h) & (_BROAD | {"*bare*"})
            ]
            if not broad:
                continue
            if any("RunCancelled" in _handler_types(h) for h in t.handlers):
                # An explicit RunCancelled arm (re-raising or a
                # DELIBERATE absorb — e.g. the job worker marking the
                # job cancelled) owns the contract; the broad arm below
                # it never sees the cancellation.
                continue
            if all(_reraises(h) for h in broad):
                continue
            # Does anything in THIS try's body (innermost-shield == this
            # try) raise RunCancelled?
            tid = id(t)
            danger = None
            for site in call_sites:
                if not site.shields or site.shields[0][0] != tid:
                    continue
                if site.callee in may_cancel:
                    danger = (site.line, graph.functions[site.callee].display())
                    break
            if danger is None:
                for rs in raise_sites:
                    if (
                        rs.exc == "RunCancelled"
                        and rs.shields
                        and rs.shields[0][0] == tid
                    ):
                        danger = (rs.line, "a direct raise")
                        break
            if danger is None:
                continue
            h = broad[0]
            findings.append(
                Finding(
                    RULE,
                    fi.rel,
                    h.lineno,
                    f"broad except absorbs RunCancelled: the try body "
                    f"calls {danger[1]} (line {danger[0]}) which may "
                    "raise it — add `except RunCancelled: raise` above, "
                    "or re-raise/capture the bound exception "
                    "(docs/lint.md \"Exception flow\")",
                )
            )

    # -- InjectedFault containment scopes --------------------------------
    for fi in graph.functions.values():
        if fi.rel in INJECTED_FAULT_SCOPES:
            continue
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.ExceptHandler) and "InjectedFault" in (
                _handler_types(sub)
            ):
                findings.append(
                    Finding(
                        RULE,
                        fi.rel,
                        sub.lineno,
                        "explicit `except InjectedFault` outside the "
                        "documented containment scopes "
                        f"({', '.join(INJECTED_FAULT_SCOPES)}) — chaos "
                        "flows through the classified SimulatorError "
                        "ladders (docs/faults.md)",
                    )
                )

    # -- ReplayFallback raise channel ------------------------------------
    for fi in graph.functions.values():
        if fi.name in FALLBACK_RAISERS:
            continue
        for rs in graph.raises.get(fi.key, ()):
            if rs.exc == "ReplayFallback":
                findings.append(
                    Finding(
                        RULE,
                        fi.rel,
                        rs.line,
                        "direct `raise ReplayFallback(...)` — fallbacks "
                        "are raised as `_Unsupported(<reason>)` or "
                        "recorded via `_reject` so every reason resolves "
                        "into FALLBACK_REASONS (registry-literals)",
                    )
                )
    return findings
