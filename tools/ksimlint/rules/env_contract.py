"""env-contract: every KSIM_* variable is documented, and vice versa.

docs/env.md is the single operator-facing table of the simulator's
environment knobs (type, default, consumer).  This rule extracts every
``KSIM_``-prefixed name appearing in any string literal of the analyzed
tree — environ reads, error messages that tell the operator which
variable to set, docstrings documenting behavior — and checks both
directions against the table:

- a name used in source but missing from docs/env.md is an
  UNDOCUMENTED knob (the scan-unroll / compile-cache / pnts-emulation
  class of drift this rule was built to end);
- a table row whose name no longer appears anywhere in source is a
  DEAD row teaching operators a knob that does nothing.

Names are matched with a full-token regex (the prefix followed by
upper-case segments, never ending in an underscore), so a starred
family glob in prose resolves to the real family root and a bare
dangling prefix never false-positives.  This module spells no variable
names anywhere (including this docstring): the analyzer's own sources
are inside the scanned tree.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from tools.ksimlint.core import Finding, Project

RULE = "env-contract"

#: Full variable tokens only: no trailing underscore, at least one
#: character after the prefix.
VAR_RE = re.compile(r"KSIM_[A-Z0-9][A-Z0-9_]*[A-Z0-9]|KSIM_[A-Z0-9]")


@dataclass(frozen=True)
class EnvConfig:
    docs_rel: str = "docs/env.md"


DEFAULT_CONFIG = EnvConfig()


def scan_env_literals(project: Project) -> dict:
    """var name -> first (rel, line) it appears at, over every string
    constant in the tree (f-string fragments included; comments are not
    string constants and are ignored)."""
    first: dict[str, tuple[str, int]] = {}
    for rel, sf in project.files.items():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for name in VAR_RE.findall(node.value):
                    first.setdefault(name, (rel, node.lineno))
    return first


def parse_docs_table(text: str) -> dict:
    """var name -> line number from the markdown table rows (any line
    starting with ``|`` whose first cell names a KSIM_ variable)."""
    documented: dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        first_cell = stripped.strip("|").split("|", 1)[0]
        for name in VAR_RE.findall(first_cell):
            documented.setdefault(name, lineno)
    return documented


def check(project: Project, cfg: EnvConfig = DEFAULT_CONFIG) -> list[Finding]:
    findings: list[Finding] = []
    used = scan_env_literals(project)
    text = project.read_text(cfg.docs_rel)
    if text is None:
        if used:
            findings.append(
                Finding(
                    RULE,
                    cfg.docs_rel,
                    1,
                    f"{cfg.docs_rel} is missing but the tree reads "
                    f"{len(used)} KSIM_* variables — write the table",
                )
            )
        return findings
    documented = parse_docs_table(text)
    for name, (rel, line) in sorted(used.items()):
        if name not in documented:
            findings.append(
                Finding(
                    RULE,
                    rel,
                    line,
                    f"{name} is read/mentioned here but undocumented — add a "
                    f"row (name, type, default, consumer) to {cfg.docs_rel}",
                )
            )
    # The dead-row direction compares the docs against the WHOLE tree;
    # on a partial run (one file, a subtree) "unused" is meaningless
    # and would mass-flag every row the slice doesn't mention.  A
    # fixture project overriding docs_rel opts back in (its docs table
    # belongs to the fixture slice by construction).
    if project.covers_default_targets() or cfg is not DEFAULT_CONFIG:
        for name, line in sorted(documented.items()):
            if name not in used:
                findings.append(
                    Finding(
                        RULE,
                        cfg.docs_rel,
                        line,
                        f"documented variable {name} appears nowhere in the "
                        "analyzed tree (dead row — delete it or wire it)",
                    )
                )
    return findings
