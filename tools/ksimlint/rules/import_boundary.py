"""import-boundary: the stdlib-only surfaces stay stdlib-only.

A module import graph over the tree proves, statically, the contracts
that today live in docstrings and CLAUDE.md prose:

- ``bench.py``'s PARENT process never imports jax/numpy/ksim_tpu — the
  one JSON line must exist under ANY hardware condition, including a
  wedged chip tunnel that hangs jax backend init.  Child payloads (the
  ``child*`` / ``_child*`` functions, which only ever run in
  subprocesses) are the sanctioned exception.
- ``tools/trace_check.py`` / ``tools/perf_table.py`` follow the same
  parent/child split.
- ``ksim_tpu/obs.py``, ``ksim_tpu/faults.py`` and ``ksim_tpu/errors.py``
  must not reach jax or numpy AT IMPORT TIME, transitively through
  their ksim_tpu-internal imports (function-scope lazy imports — the
  guarded ``jax.profiler`` bridge — stay legal).  This is what lets the
  fault/trace planes configure themselves from the environment inside
  stdlib-only subprocess parents.
- ``tools/ksimlint`` itself may import NOTHING outside the stdlib: the
  analyzer must run in any environment and must never execute the code
  it analyzes.

Scopes:

- ``import-time``: module-scope imports only (including class bodies
  and top-level if/try blocks; ``if TYPE_CHECKING:`` is skipped),
  chased transitively through ksim_tpu-internal modules — the finding
  message carries the offending import chain.
- ``parent-child``: module scope must be clean, and function-scope
  forbidden imports are only legal inside top-level functions whose
  name starts with ``child``/``_child``.
- ``everywhere``: no forbidden import at any scope.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.ksimlint.core import Finding, Project, SourceFile

RULE = "import-boundary"

_ACCEL = frozenset({"jax", "jaxlib", "numpy"})


@dataclass(frozen=True)
class Boundary:
    target: str  # file or directory prefix, repo-relative posix
    forbidden: frozenset[str]  # top-level package names
    scope: str  # "import-time" | "parent-child" | "everywhere"
    child_prefixes: tuple[str, ...] = ("child", "_child")


DEFAULT_BOUNDARIES: tuple[Boundary, ...] = (
    Boundary("bench.py", _ACCEL | {"ksim_tpu"}, "parent-child"),
    Boundary("tools/trace_check.py", _ACCEL | {"ksim_tpu"}, "parent-child"),
    Boundary("tools/perf_table.py", _ACCEL | {"ksim_tpu"}, "parent-child"),
    Boundary("tools/ksimlint", _ACCEL | {"ksim_tpu", "tests"}, "everywhere"),
    Boundary("ksim_tpu/obs.py", _ACCEL, "import-time"),
    Boundary("ksim_tpu/faults.py", _ACCEL, "import-time"),
    Boundary("ksim_tpu/errors.py", _ACCEL, "import-time"),
    # The trace ingestion plane: parsers/registry/resample must stay
    # stdlib-only at import time (they configure and fail cleanly in
    # jax-free processes — the bench parent, the HTTP surface); jax may
    # enter only through the compile path's function-scope imports.
    Boundary("ksim_tpu/traces", _ACCEL, "import-time"),
)


def _is_type_checking(test: ast.expr) -> bool:
    return (isinstance(test, ast.Name) and test.id == "TYPE_CHECKING") or (
        isinstance(test, ast.Attribute) and test.attr == "TYPE_CHECKING"
    )


def _resolve_import_from(node: ast.ImportFrom, rel: str) -> list[str]:
    """Dotted module names an ImportFrom reaches, with RELATIVE imports
    resolved against the importing file's package (a relative import is
    just spelling — it must not bypass the boundary).  Each alias is
    also emitted as a possible submodule (``from .engine import replay``
    imports ksim_tpu.engine.replay); non-module aliases resolve to no
    file downstream and are harmless."""
    if node.level == 0:
        return [node.module] if node.module else []
    dir_parts = rel.split("/")[:-1]
    base = dir_parts[: len(dir_parts) - (node.level - 1)]
    if not base or len(dir_parts) < node.level - 1:
        return []  # relative import escaping the scanned tree
    if node.module:
        base = base + node.module.split(".")
    prefix = ".".join(base)
    return [prefix] + [f"{prefix}.{a.name}" for a in node.names if a.name != "*"]


def module_scope_imports(tree: ast.Module, rel: str = "") -> list[tuple[str, int]]:
    """(module, line) for every import executed at import time: module
    scope, class bodies, top-level if/try/with — NOT function bodies,
    NOT ``if TYPE_CHECKING:`` branches.  Relative imports resolve
    against ``rel``'s package."""
    out: list[tuple[str, int]] = []

    def walk(stmts) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(s, ast.Import):
                out.extend((a.name, s.lineno) for a in s.names)
            elif isinstance(s, ast.ImportFrom):
                out.extend((m, s.lineno) for m in _resolve_import_from(s, rel))
            elif isinstance(s, ast.If):
                if not _is_type_checking(s.test):
                    walk(s.body)
                walk(s.orelse)
            elif isinstance(s, ast.Try):
                walk(s.body)
                for h in s.handlers:
                    walk(h.body)
                walk(s.orelse)
                walk(s.finalbody)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                walk(s.body)
            elif isinstance(s, ast.ClassDef):
                walk(s.body)

    walk(tree.body)
    return out


def _internal_files(project: Project, module: str) -> list[str]:
    """Repo files executed when ``module`` (dotted, ksim_tpu-internal)
    is imported: every ancestor package __init__ plus the module file."""
    parts = module.split(".")
    files: list[str] = []
    for i in range(1, len(parts) + 1):
        prefix = "/".join(parts[:i])
        if i < len(parts):
            files.append(f"{prefix}/__init__.py")
        else:
            if f"{prefix}/__init__.py" in project.files:
                files.append(f"{prefix}/__init__.py")
            elif f"{prefix}.py" in project.files:
                files.append(f"{prefix}.py")
    return [f for f in files if f in project.files]


def _import_time_chain(
    project: Project,
    rel: str,
    forbidden: frozenset[str],
    seen: dict[str, "list[str] | None"],
) -> "list[str] | None":
    """DFS: the first chain of module-scope imports from ``rel`` that
    reaches a forbidden top-level package, or None.  ``seen`` memoizes
    per-file results (None = proven clean)."""
    if rel in seen:
        return seen[rel]
    seen[rel] = None  # cycle guard: a cycle cannot introduce new imports
    sf = project.files.get(rel)
    if sf is None:
        return None
    for module, line in module_scope_imports(sf.tree, rel):
        top = module.partition(".")[0]
        if top in forbidden:
            chain = [f"{rel}:{line} imports {module}"]
            seen[rel] = chain
            return chain
        # Follow any module that resolves to a file in the analyzed
        # tree (stdlib and third-party names resolve to nothing).
        for sub in _internal_files(project, module):
            if sub == rel:
                continue
            tail = _import_time_chain(project, sub, forbidden, seen)
            if tail:
                chain = [f"{rel}:{line} imports {module}"] + tail
                seen[rel] = chain
                return chain
    return None


def _first_line(chain: list[str]) -> int:
    # "path:LINE imports x" -> LINE of the boundary file's own import
    return int(chain[0].split(" ", 1)[0].rsplit(":", 1)[1])


def _check_import_time(
    project: Project, sf: SourceFile, b: Boundary, findings: list[Finding]
) -> None:
    chain = _import_time_chain(project, sf.rel, b.forbidden, {})
    if chain:
        findings.append(
            Finding(
                RULE,
                sf.rel,
                _first_line(chain),
                f"{sf.rel} must not reach {{{', '.join(sorted(b.forbidden))}}} "
                f"at import time: {' -> '.join(chain)}",
            )
        )


def _all_imports(node, rel: str) -> list[tuple[str, int]]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Import):
            out.extend((a.name, sub.lineno) for a in sub.names)
        elif isinstance(sub, ast.ImportFrom):
            out.extend((m, sub.lineno) for m in _resolve_import_from(sub, rel))
    return out


def _check_parent_child(sf: SourceFile, b: Boundary, findings: list[Finding]) -> None:
    for module, line in module_scope_imports(sf.tree, sf.rel):
        if module.partition(".")[0] in b.forbidden:
            findings.append(
                Finding(
                    RULE,
                    sf.rel,
                    line,
                    f"stdlib-only parent imports {module} at module scope "
                    "(move it into a child payload function)",
                )
            )
    for stmt in sf.tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if stmt.name.startswith(b.child_prefixes):
            continue  # sanctioned child payload: runs only in subprocesses
        for module, line in _all_imports(stmt, sf.rel):
            if module.partition(".")[0] in b.forbidden:
                findings.append(
                    Finding(
                        RULE,
                        sf.rel,
                        line,
                        f"parent-side function {stmt.name!r} imports {module} "
                        f"(only child payload functions "
                        f"({'/'.join(b.child_prefixes)}*) may)",
                    )
                )


def _check_everywhere(sf: SourceFile, b: Boundary, findings: list[Finding]) -> None:
    for module, line in _all_imports(sf.tree, sf.rel):
        if module.partition(".")[0] in b.forbidden:
            findings.append(
                Finding(
                    RULE,
                    sf.rel,
                    line,
                    f"{sf.rel} is stdlib-only but imports {module}",
                )
            )


def check(
    project: Project, boundaries: tuple[Boundary, ...] = DEFAULT_BOUNDARIES
) -> list[Finding]:
    findings: list[Finding] = []
    for b in boundaries:
        for rel, sf in project.files.items():
            if not (rel == b.target or rel.startswith(b.target.rstrip("/") + "/")):
                continue
            if b.scope == "import-time":
                _check_import_time(project, sf, b, findings)
            elif b.scope == "parent-child":
                _check_parent_child(sf, b, findings)
            elif b.scope == "everywhere":
                _check_everywhere(sf, b, findings)
            else:  # pragma: no cover - config error
                raise ValueError(f"unknown boundary scope {b.scope!r}")
    return findings
