"""Rule registry: every module here exposes RULE + check(project)."""

from tools.ksimlint.rules import (
    env_contract,
    exception_flow,
    import_boundary,
    kernel_purity,
    lock_discipline,
    lock_order,
    registry_literals,
    thread_role,
)

_MODULES = (
    lock_discipline,
    lock_order,
    thread_role,
    exception_flow,
    kernel_purity,
    import_boundary,
    registry_literals,
    env_contract,
)

ALL_RULES = {m.RULE: m.check for m in _MODULES}

#: Rule id -> first docstring line (the SARIF shortDescription).
RULE_DOCS = {
    m.RULE: (m.__doc__ or "").strip().splitlines()[0] for m in _MODULES
}

__all__ = ["ALL_RULES", "RULE_DOCS"]
