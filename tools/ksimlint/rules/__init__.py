"""Rule registry: every module here exposes RULE + check(project)."""

from tools.ksimlint.rules import (
    env_contract,
    import_boundary,
    kernel_purity,
    lock_discipline,
    registry_literals,
)

_MODULES = (
    lock_discipline,
    kernel_purity,
    import_boundary,
    registry_literals,
    env_contract,
)

ALL_RULES = {m.RULE: m.check for m in _MODULES}

__all__ = ["ALL_RULES"]
