"""lock-discipline: ``# guarded-by:`` state only touched under its lock.

The Clang thread-safety (``guarded_by``) idea over Python ASTs, scoped
to what this codebase actually relies on (docs/lint.md "Lock
discipline"):

- An attribute initialized in ``__init__`` with a trailing
  ``# guarded-by: <lock>`` comment may only be read or written through
  ``self.<attr>`` inside a ``with self.<lock>:`` block, or inside a
  method annotated ``# ksimlint: lock-held(<lock>)`` (a helper whose
  documented contract is "callers hold the lock").  ``__init__`` itself
  is exempt: construction happens-before publication.
- A module-level name annotated ``# guarded-by: <lock>`` may only be
  used inside functions under ``with <lock>:`` (module scope itself is
  exempt — that is single-threaded import time).
- ``# guarded-by: main-thread`` declares thread-confined state (the
  ReplayDriver's worker/prelower bookkeeping): no lock exists, the
  contract is that only the owning thread writes it.  Enforcement rides
  on the worker rule below; the annotation also documents the attribute
  for readers.
- A function annotated ``# ksimlint: worker-thread`` (the replay
  dispatch worker and ``ReplayDriver._run``) must be side-effect-free
  on its instance: NO store to any ``self.<attr>`` — the round-8
  containment contract that lets an abandoned watchdog worker finish
  late without corrupting the degraded run's accounting.

Lexical soundness limits (accepted, documented in docs/lint.md): calls
are not followed (a lock-held helper calling an unannotated mutator is
checked at the mutator, not the call), nested ``def``/``lambda`` bodies
conservatively reset the held-lock set (a closure may run after the
``with`` exits), and cross-object accesses (``other.store._x``) are out
of scope.
"""

from __future__ import annotations

import ast
import re

from tools.ksimlint.core import Finding, Project, SourceFile

RULE = "lock-discipline"

GUARD_RE = re.compile(r"guarded-by:\s*([A-Za-z_][\w-]*)")
LOCK_HELD_RE = re.compile(r"ksimlint:\s*lock-held\(([A-Za-z_]\w*)\)")
WORKER_RE = re.compile(r"ksimlint:\s*worker-thread")

MAIN_THREAD = "main-thread"

_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)


def _stmt_guard(sf: SourceFile, stmt: ast.stmt) -> "str | None":
    """The guarded-by annotation on an assignment: a trailing comment on
    any of the statement's lines, or a comment-only line directly above
    (for assignments whose first line has no room)."""
    start = stmt.lineno
    if start - 1 in sf.comment_only:
        start -= 1
    m = sf.directive_in_range(start, getattr(stmt, "end_lineno", stmt.lineno), GUARD_RE)
    return m.group(1) if m else None


def _def_directive(sf: SourceFile, fn, pattern: re.Pattern):
    """Match a directive on the ``def`` line span (signature lines up to
    the first body statement)."""
    end = fn.body[0].lineno - 1 if fn.body else fn.lineno
    return sf.directive_in_range(fn.lineno, max(fn.lineno, end), pattern)


def _assign_targets(stmt: ast.stmt):
    if isinstance(stmt, ast.Assign):
        return stmt.targets
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return [stmt.target]
    return []


def _is_self_attr(node: ast.expr) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _with_held(stmt, lock_exprs: dict[str, str]) -> set[str]:
    """Lock names among ``lock_exprs`` acquired by this With statement
    (matched on the unparsed context expression, e.g. ``self._lock``)."""
    held: set[str] = set()
    for item in stmt.items:
        expr = ast.unparse(item.context_expr)
        for lock, text in lock_exprs.items():
            if expr == text:
                held.add(lock)
    return held


class _AccessChecker(ast.NodeVisitor):
    """Walk one function body tracking lexically held locks."""

    def __init__(
        self,
        sf: SourceFile,
        guards: dict[str, str],
        lock_exprs: dict[str, str],
        held: frozenset[str],
        self_attr: bool,
        findings: list[Finding],
    ) -> None:
        self.sf = sf
        self.guards = guards  # attr/name -> lock
        self.lock_exprs = lock_exprs  # lock -> unparse text to match in With
        self.held = held
        self.self_attr = self_attr  # True: guard self.<attr>; False: bare names
        self.findings = findings

    def _sub(self, held: frozenset[str]) -> "_AccessChecker":
        return _AccessChecker(
            self.sf, self.guards, self.lock_exprs, held, self.self_attr, self.findings
        )

    # -- scope / lock structure -----------------------------------------

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        inner = self._sub(self.held | _with_held(node, self.lock_exprs))
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncWith = visit_With

    def _visit_nested(self, node) -> None:
        # Conservative: a nested def/lambda may execute after the
        # enclosing with block exits — it inherits nothing, unless it
        # carries its own lock-held annotation.
        held: frozenset[str] = frozenset()
        if not isinstance(node, ast.Lambda):
            m = _def_directive(self.sf, node, LOCK_HELD_RE)
            if m:
                held = frozenset({m.group(1)})
        inner = self._sub(held)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            inner.visit(stmt)

    def visit_FunctionDef(self, node) -> None:
        self._visit_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- the accesses ----------------------------------------------------

    def _flag(self, node, name: str, lock: str) -> None:
        what = f"self.{name}" if self.self_attr else name
        self.findings.append(
            Finding(
                RULE,
                self.sf.rel,
                node.lineno,
                f"{what} is guarded by {lock!r} but accessed without "
                f"holding it (wrap in `with {self.lock_exprs[lock]}:` or "
                f"annotate the method `# ksimlint: lock-held({lock})`)",
            )
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.self_attr and _is_self_attr(node):
            lock = self.guards.get(node.attr)
            if lock is not None and lock != MAIN_THREAD and lock not in self.held:
                self._flag(node, node.attr, lock)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if not self.self_attr:
            lock = self.guards.get(node.id)
            if lock is not None and lock != MAIN_THREAD and lock not in self.held:
                self._flag(node, node.id, lock)


def _class_guards(sf: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock from annotated assignments in __init__ (and annotated
    class-body assignments)."""
    guards: dict[str, str] = {}
    for stmt in cls.body:
        if isinstance(stmt, _FUNC) and stmt.name == "__init__":
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    lock = _stmt_guard(sf, sub)
                    if lock:
                        for tgt in _assign_targets(sub):
                            if _is_self_attr(tgt):
                                guards[tgt.attr] = lock
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            lock = _stmt_guard(sf, stmt)
            if lock:
                for tgt in _assign_targets(stmt):
                    if isinstance(tgt, ast.Name):
                        guards[tgt.id] = lock
    return guards


def _check_class(sf: SourceFile, cls: ast.ClassDef, findings: list[Finding]) -> None:
    guards = _class_guards(sf, cls)
    if not guards:
        return
    lock_exprs = {
        lock: f"self.{lock}" for lock in set(guards.values()) if lock != MAIN_THREAD
    }
    for stmt in cls.body:
        if not isinstance(stmt, _FUNC) or stmt.name == "__init__":
            continue
        held: frozenset[str] = frozenset()
        m = _def_directive(sf, stmt, LOCK_HELD_RE)
        if m:
            held = frozenset({m.group(1)})
        checker = _AccessChecker(sf, guards, lock_exprs, held, True, findings)
        for sub in stmt.body:
            checker.visit(sub)


def _check_module_guards(sf: SourceFile, findings: list[Finding]) -> None:
    guards: dict[str, str] = {}
    for stmt in sf.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            lock = _stmt_guard(sf, stmt)
            if lock:
                for tgt in _assign_targets(stmt):
                    if isinstance(tgt, ast.Name):
                        guards[tgt.id] = lock
    if not guards:
        return
    lock_exprs = {lock: lock for lock in set(guards.values()) if lock != MAIN_THREAD}
    # Every function at module OR class scope (methods touch module
    # globals too); functions nested inside functions are reached by
    # the checker's own recursion, not enumerated here.
    def outer_functions(stmts):
        for stmt in stmts:
            if isinstance(stmt, _FUNC):
                yield stmt
            elif isinstance(stmt, ast.ClassDef):
                yield from outer_functions(stmt.body)

    for stmt in outer_functions(sf.tree.body):
        held: frozenset[str] = frozenset()
        m = _def_directive(sf, stmt, LOCK_HELD_RE)
        if m:
            held = frozenset({m.group(1)})
        checker = _AccessChecker(sf, guards, lock_exprs, held, False, findings)
        for sub in stmt.body:
            checker.visit(sub)


def _check_worker_functions(sf: SourceFile, findings: list[Finding]) -> None:
    for node in ast.walk(sf.tree):
        if isinstance(node, _FUNC) and _def_directive(sf, node, WORKER_RE):
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Attribute)
                    and _is_self_attr(sub)
                    and isinstance(sub.ctx, (ast.Store, ast.Del))
                ):
                    findings.append(
                        Finding(
                            RULE,
                            sf.rel,
                            sub.lineno,
                            f"worker-thread function {node.name!r} writes "
                            f"self.{sub.attr} — dispatch workers must be "
                            "side-effect-free on the instance (apply state "
                            "on the main thread after join)",
                        )
                    )


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(sf, node, findings)
        _check_module_guards(sf, findings)
        _check_worker_functions(sf, findings)
    return findings
