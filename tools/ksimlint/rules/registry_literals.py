"""registry-literals: every taxonomy string resolves into its registry.

The trace/fault planes and the replay fallback histogram are keyed by
string literals spelled at call sites; the registries
(``faults.SITES``, ``obs.SPAN_NAMES`` / ``EVENT_NAMES``,
``engine.replay.FALLBACK_REASONS`` / ``FALLBACK_REASON_PREFIXES``) are
what docs, dashboards and the registry-sync tests consume.  This rule
scans every call site in the tree and checks BOTH directions:

- every ``FAULTS.check("...")`` literal is a declared site, and every
  declared site is wired somewhere (a dead registry entry is a lie in
  the docs);
- every declared site has a same-named span (a fault event always has
  an enclosing phase on the timeline);
- every ``TRACE.span("...")`` / ``TRACE.event("...")`` name is in the
  span/event taxonomy;
- every ``_expo_family("...")`` Prometheus exposition family declared
  in obs.py resolves into ``obs.METRIC_NAMES`` (and every registry
  entry is declared somewhere — a family in the registry with no
  exposition declaration would be a scrape-dashboard lie);
- every static ``_reject("...")`` / ``_Unsupported("...")`` reason in
  engine/replay.py is in FALLBACK_REASONS (and f-string reason families
  match FALLBACK_REASON_PREFIXES); registry entries must appear in the
  source as a call reason or a returned discard string;
- a NON-literal first argument to any of these calls is itself a
  finding: the registries can only vouch for strings the AST can see.

The registries are read from the defining modules' ASTs (never by
import), so the analyzer stays stdlib-only; tests/test_obs.py
cross-checks this AST view against the imported runtime values, and the
former grep-based registry-sync tests are re-backed by the scan
functions below.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from tools.ksimlint.core import Finding, Project

RULE = "registry-literals"


@dataclass(frozen=True)
class RegistryConfig:
    """Where the registries and their call sites live (overridable so
    fixture mini-trees can exercise the rule)."""

    faults_module: str = "ksim_tpu/faults.py"
    obs_module: str = "ksim_tpu/obs.py"
    replay_module: str = "ksim_tpu/engine/replay.py"
    faults_object: str = "FAULTS"  # <obj>.check(site)
    trace_object: str = "TRACE"  # <obj>.span(name) / <obj>.event(name)
    metric_helper: str = "_expo_family"  # <helper>(family, kind, help)


DEFAULT_CONFIG = RegistryConfig()


@dataclass(frozen=True)
class Registries:
    sites: tuple[str, ...]
    sites_line: int
    span_names: tuple[str, ...]
    event_names: tuple[str, ...]
    metric_names: tuple[str, ...]
    metric_names_line: int
    fallback_reasons: frozenset[str]
    fallback_reasons_line: int
    fallback_prefixes: tuple[str, ...]


def _literal_assignment(tree: ast.Module, name: str):
    """(value, line) of a module-level ``NAME = <literal>`` assignment;
    unwraps a single ``frozenset(...)`` / ``tuple(...)`` call."""
    for stmt in tree.body:
        value = None
        if isinstance(stmt, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == name for t in stmt.targets):
                value = stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            if isinstance(stmt.target, ast.Name) and stmt.target.id == name:
                value = stmt.value
        if value is None:
            continue
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id in ("frozenset", "tuple", "set")
            and len(value.args) == 1
        ):
            value = value.args[0]
        return ast.literal_eval(value), stmt.lineno
    raise KeyError(name)


def load_registries(project: Project, cfg: RegistryConfig = DEFAULT_CONFIG) -> Registries:
    faults = project.files[cfg.faults_module].tree
    obs = project.files[cfg.obs_module].tree
    replay = project.files[cfg.replay_module].tree
    sites, sites_line = _literal_assignment(faults, "SITES")
    span_names, _ = _literal_assignment(obs, "SPAN_NAMES")
    event_names, _ = _literal_assignment(obs, "EVENT_NAMES")
    metric_names, metric_names_line = _literal_assignment(obs, "METRIC_NAMES")
    reasons, reasons_line = _literal_assignment(replay, "FALLBACK_REASONS")
    prefixes, _ = _literal_assignment(replay, "FALLBACK_REASON_PREFIXES")
    return Registries(
        sites=tuple(sites),
        sites_line=sites_line,
        span_names=tuple(span_names),
        event_names=tuple(event_names),
        metric_names=tuple(metric_names),
        metric_names_line=metric_names_line,
        fallback_reasons=frozenset(reasons),
        fallback_reasons_line=reasons_line,
        fallback_prefixes=tuple(prefixes),
    )


def _method_calls(project: Project, obj: str, method: str, skip: frozenset[str]):
    """Every ``<obj>.<method>(...)`` call in the tree (minus ``skip``
    files): yields (rel, call node)."""
    for rel, sf in project.files.items():
        if rel in skip:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == method
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == obj
            ):
                yield rel, node


@dataclass
class LiteralScan:
    """Call-site literals: value -> [(rel, line)], plus non-literal
    call sites the registries cannot vouch for."""

    literals: dict
    dynamic: list

    def __init__(self) -> None:
        self.literals = {}
        self.dynamic = []

    def add(self, rel: str, node: ast.Call) -> None:
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self.literals.setdefault(arg.value, []).append((rel, node.lineno))
        else:
            self.dynamic.append((rel, node.lineno))


def scan_fault_sites(
    project: Project, cfg: RegistryConfig = DEFAULT_CONFIG
) -> LiteralScan:
    """Every ``FAULTS.check(...)`` call site (the declaring module is
    excluded: it defines the idiom, the wiring lives elsewhere)."""
    scan = LiteralScan()
    for rel, node in _method_calls(
        project, cfg.faults_object, "check", frozenset({cfg.faults_module})
    ):
        scan.add(rel, node)
    return scan


def _function_calls(project: Project, fname: str):
    """Every bare ``<fname>(...)`` call in the tree: yields (rel, call
    node).  The attribute-call spelling is out of scope on purpose —
    the exposition helper is module-local by construction."""
    for rel, sf in project.files.items():
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == fname
            ):
                yield rel, node


def scan_metric_literals(
    project: Project, cfg: RegistryConfig = DEFAULT_CONFIG
) -> LiteralScan:
    """Every ``_expo_family(...)`` exposition-family declaration — the
    lint-scannable spelling of the `/metrics` surface."""
    scan = LiteralScan()
    for rel, node in _function_calls(project, cfg.metric_helper):
        scan.add(rel, node)
    return scan


def scan_trace_literals(
    project: Project, cfg: RegistryConfig = DEFAULT_CONFIG
) -> tuple[LiteralScan, LiteralScan]:
    """(span call sites, event call sites) for the trace plane."""
    spans, events = LiteralScan(), LiteralScan()
    for rel, node in _method_calls(project, cfg.trace_object, "span", frozenset()):
        spans.add(rel, node)
    for rel, node in _method_calls(project, cfg.trace_object, "event", frozenset()):
        events.add(rel, node)
    return spans, events


@dataclass
class FallbackScan:
    call_reasons: dict  # literal -> [(rel, line)]
    fstring_prefixes: dict  # leading text of f-string reasons -> [(rel, line)]
    return_strings: frozenset  # every string returned anywhere in the module


def scan_fallback_reasons(
    project: Project, cfg: RegistryConfig = DEFAULT_CONFIG
) -> FallbackScan:
    """Static ``_reject(...)`` / ``_Unsupported(...)`` reasons in the
    replay module (the exact scan the registry-sync test used to
    implement inline with re+ast)."""
    rel = cfg.replay_module
    tree = project.files[rel].tree
    call_reasons: dict = {}
    fstring_prefixes: dict = {}
    return_strings: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fname = (
                node.func.id
                if isinstance(node.func, ast.Name)
                else getattr(node.func, "attr", "")
            )
            if fname in ("_Unsupported", "_reject") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    call_reasons.setdefault(arg.value, []).append((rel, node.lineno))
                elif isinstance(arg, ast.JoinedStr) and arg.values and isinstance(
                    arg.values[0], ast.Constant
                ):
                    fstring_prefixes.setdefault(str(arg.values[0].value), []).append(
                        (rel, node.lineno)
                    )
        elif (
            isinstance(node, ast.Return)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            return_strings.add(node.value.value)
    return FallbackScan(call_reasons, fstring_prefixes, frozenset(return_strings))


def check(project: Project, cfg: RegistryConfig = DEFAULT_CONFIG) -> list[Finding]:
    findings: list[Finding] = []
    registry_modules = (cfg.faults_module, cfg.obs_module, cfg.replay_module)
    present = [m for m in registry_modules if m in project.files]
    if len(present) < len(registry_modules):
        # On the full default tree a missing registry module is a real
        # structural finding; on a partial run (one file, a subtree)
        # the registries are simply out of scope and the rule does not
        # apply.
        if project.covers_default_targets():
            for m in registry_modules:
                if m not in project.files:
                    findings.append(
                        Finding(
                            RULE,
                            m,
                            1,
                            f"registry module {m} missing from the analyzed tree",
                        )
                    )
        return findings
    try:
        regs = load_registries(project, cfg)
    except KeyError as e:
        return [
            Finding(RULE, cfg.faults_module, 1, f"registry {e} not found in source")
        ]

    def flag(rel: str, line: int, msg: str) -> None:
        findings.append(Finding(RULE, rel, line, msg))

    # -- fault sites -----------------------------------------------------
    sites = frozenset(regs.sites)
    scan = scan_fault_sites(project, cfg)
    for value, locs in sorted(scan.literals.items()):
        if value not in sites:
            for rel, line in locs:
                flag(rel, line, f"FAULTS.check site {value!r} is not declared in SITES")
    for rel, line in scan.dynamic:
        flag(rel, line, "FAULTS.check with a non-literal site name (unverifiable)")
    for site in regs.sites:
        if site not in scan.literals:
            flag(
                cfg.faults_module,
                regs.sites_line,
                f"SITES entry {site!r} has no FAULTS.check call site",
            )
        if site not in regs.span_names:
            flag(
                cfg.faults_module,
                regs.sites_line,
                f"SITES entry {site!r} has no same-named span in SPAN_NAMES",
            )

    # -- trace names -----------------------------------------------------
    spans, events = scan_trace_literals(project, cfg)
    for value, locs in sorted(spans.literals.items()):
        if value not in regs.span_names:
            for rel, line in locs:
                flag(rel, line, f"span name {value!r} is not in obs.SPAN_NAMES")
    for value, locs in sorted(events.literals.items()):
        if value not in regs.event_names:
            for rel, line in locs:
                flag(rel, line, f"event name {value!r} is not in obs.EVENT_NAMES")
    for kind, scan_ in (("span", spans), ("event", events)):
        for rel, line in scan_.dynamic:
            flag(rel, line, f"TRACE.{kind} with a non-literal name (unverifiable)")

    # -- exposition metric families --------------------------------------
    metrics = scan_metric_literals(project, cfg)
    metric_names = frozenset(regs.metric_names)
    for value, locs in sorted(metrics.literals.items()):
        if value not in metric_names:
            for rel, line in locs:
                flag(
                    rel,
                    line,
                    f"exposition family {value!r} is not in obs.METRIC_NAMES",
                )
    for rel, line in metrics.dynamic:
        flag(
            rel,
            line,
            f"{cfg.metric_helper} with a non-literal family name (unverifiable)",
        )
    for name in regs.metric_names:
        if name not in metrics.literals:
            flag(
                cfg.obs_module,
                regs.metric_names_line,
                f"METRIC_NAMES entry {name!r} has no {cfg.metric_helper} "
                "declaration (dead registry entry)",
            )

    # -- fallback reasons ------------------------------------------------
    fb = scan_fallback_reasons(project, cfg)
    for value, locs in sorted(fb.call_reasons.items()):
        if value not in regs.fallback_reasons:
            for rel, line in locs:
                flag(rel, line, f"fallback reason {value!r} not in FALLBACK_REASONS")
    for prefix, locs in sorted(fb.fstring_prefixes.items()):
        if not any(prefix.startswith(p) for p in regs.fallback_prefixes):
            for rel, line in locs:
                flag(
                    rel,
                    line,
                    f"dynamic fallback reason family {prefix!r} not covered by "
                    "FALLBACK_REASON_PREFIXES",
                )
    dead = regs.fallback_reasons - set(fb.call_reasons) - fb.return_strings
    for reason in sorted(dead):
        flag(
            cfg.replay_module,
            regs.fallback_reasons_line,
            f"FALLBACK_REASONS entry {reason!r} appears nowhere in "
            f"{cfg.replay_module} (dead registry entry)",
        )
    # Registry-definition invariants the event taxonomy depends on.
    for required in ("fault.fired", "replay.fallback"):
        if required not in regs.event_names:
            flag(
                cfg.obs_module,
                1,
                f"EVENT_NAMES must contain {required!r} (fault/fallback "
                "timeline evidence)",
            )
    return findings
