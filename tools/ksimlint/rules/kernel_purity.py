"""kernel-purity: ``@device_kernel`` trace-time bodies stay pure.

The byte-identical churn locks (repo CLAUDE.md) rest on the device
kernels being (a) free of host effects — a ``print`` or ``.item()``
inside a traced body forces a device sync or fails under jit — and
(b) f32-deterministic — no hardcoded 64-bit dtypes, no host-numpy math
on traced values, no Python control flow on traced values (which either
crashes at trace time or, worse, silently bakes one branch into the
compiled program).

Kernels are DECLARED, not guessed: the runtime registry decorator
``ksim_tpu.engine.kernelreg.device_kernel`` marks every scan body /
jitted program builder, and its ``static=(...)`` names mirror the
``jax.jit`` static arguments (trace-time Python values — branching on
them is fine and common).  This rule finds the decorator in the AST, so
the analyzer never imports the engine.

Checks, over the kernel body INCLUDING nested defs (scan bodies,
``lax.cond`` branches):

- ``print(...)`` calls;
- ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` calls;
- ``float()`` / ``int()`` / ``bool()`` applied to a traced value;
- ``np.*`` / ``numpy.*`` calls applied to a traced value (host math on
  a tracer; static shape arithmetic with numpy stays legal);
- references to 64-bit dtypes (``.float64`` / ``.int64`` attributes or
  ``"float64"`` / ``"int64"`` literals) — exact mode enables x64
  globally via jax.config, never by hardcoding dtypes in kernels;
- ``if`` / ``while`` / ``assert`` statements whose test involves a
  traced value (use ``lax.cond`` / ``jnp.where``).

"Traced" is a name-level taint: every parameter of the kernel (minus
the declared statics) and of any nested def seeds the set; assignment
from a tainted expression taints the targets.  Closure variables and
statics are trace-time Python — branching on them is not flagged.
"""

from __future__ import annotations

import ast

from tools.ksimlint.core import Finding, Project, SourceFile

RULE = "kernel-purity"

DECORATOR = "device_kernel"

_HOST_METHODS = frozenset({"item", "tolist", "block_until_ready"})
_COERCIONS = frozenset({"float", "int", "bool"})
_NUMPY_NAMES = frozenset({"np", "numpy"})
_WIDE_DTYPES = frozenset({"float64", "int64"})
_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)


def _decorator_statics(fn) -> "tuple[str, ...] | None":
    """The declared static names if ``fn`` carries @device_kernel (with
    or without arguments); None when it is not a registered kernel."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.id if isinstance(target, ast.Name) else getattr(target, "attr", "")
        if name != DECORATOR:
            continue
        statics: list[str] = []
        if isinstance(dec, ast.Call):
            for kw in dec.keywords:
                if kw.arg == "static" and isinstance(kw.value, (ast.Tuple, ast.List)):
                    statics = [
                        e.value
                        for e in kw.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    ]
        return tuple(statics)
    return None


def _param_names(fn) -> list[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _names_in(expr: ast.expr) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def scan_kernels(sf: SourceFile) -> "list[tuple[ast.AST, tuple[str, ...]]]":
    """Every @device_kernel def in the file with its static names (the
    analyzer-side view of the runtime KERNELS registry; tests cross-check
    the two)."""
    out = []
    for node in ast.walk(sf.tree):
        if isinstance(node, _FUNC):
            statics = _decorator_statics(node)
            if statics is not None:
                out.append((node, statics))
    return out


class _KernelChecker:
    def __init__(self, sf: SourceFile, kernel, statics: tuple[str, ...]) -> None:
        self.sf = sf
        self.kernel = kernel
        self.tainted: set[str] = set(
            n for n in _param_names(kernel) if n not in statics
        )
        self.findings: list[Finding] = []

    def _flag(self, node, message: str) -> None:
        self.findings.append(
            Finding(
                RULE,
                self.sf.rel,
                node.lineno,
                f"kernel {self.kernel.name!r}: {message}",
            )
        )

    def _is_tainted(self, expr: ast.expr) -> bool:
        return bool(_names_in(expr) & self.tainted)

    def _taint_target(self, target: ast.expr) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                self.tainted.add(n.id)

    # -- expression checks ----------------------------------------------

    def _check_expr(self, expr: ast.expr) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Name):
                    if func.id == "print":
                        self._flag(node, "print() inside a traced body")
                    elif func.id in _COERCIONS and any(
                        self._is_tainted(a) for a in node.args
                    ):
                        self._flag(
                            node,
                            f"{func.id}() coerces a traced value to a host "
                            "scalar (forces a sync / fails under jit)",
                        )
                elif isinstance(func, ast.Attribute):
                    if (
                        func.attr in _HOST_METHODS
                        and not node.args
                        # Only on traced receivers: trace-time host prep
                        # on a static value (st.mask_np.tolist()) is
                        # legal Python, like every other check here.
                        and self._is_tainted(func.value)
                    ):
                        self._flag(
                            node, f".{func.attr}() on a traced value is a host sync"
                        )
                    elif (
                        isinstance(func.value, ast.Name)
                        and func.value.id in _NUMPY_NAMES
                        and any(self._is_tainted(a) for a in node.args)
                    ):
                        self._flag(
                            node,
                            f"host numpy op {ast.unparse(func)} applied to a "
                            "traced value (use jnp)",
                        )
            elif isinstance(node, ast.Attribute) and node.attr in _WIDE_DTYPES:
                self._flag(
                    node,
                    f"64-bit dtype .{node.attr} hardcoded in a kernel (exact "
                    "mode flips jax_enable_x64 globally; kernels stay "
                    "dtype-agnostic for the f32 determinism contract)",
                )
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value in _WIDE_DTYPES
            ):
                self._flag(node, f"64-bit dtype literal {node.value!r} in a kernel")

    # -- statements ------------------------------------------------------

    def check_body(self, stmts: list[ast.stmt]) -> None:
        for stmt in stmts:
            self._check_stmt(stmt)

    def _check_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, _FUNC):
            # Nested defs are scan bodies / cond branches: every
            # parameter is traced (scan carries, branch operands).
            self.tainted.update(_param_names(stmt))
            self.check_body(stmt.body)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            if self._is_tainted(stmt.test):
                self._flag(
                    stmt,
                    "Python branch on a traced value (lax.cond / jnp.where "
                    "keep it on-device)",
                )
            self._check_expr(stmt.test)
            self.check_body(stmt.body)
            self.check_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Assert):
            if self._is_tainted(stmt.test):
                self._flag(stmt, "assert on a traced value")
            self._check_expr(stmt.test)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                self._check_expr(value)
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                if self._is_tainted(value) or isinstance(stmt, ast.AugAssign):
                    for t in targets:
                        self._taint_target(t)
            return
        if isinstance(stmt, ast.For):
            self._check_expr(stmt.iter)
            if self._is_tainted(stmt.iter):
                self._taint_target(stmt.target)
            self.check_body(stmt.body)
            self.check_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            self.check_body(stmt.body)
            return
        if isinstance(stmt, ast.Match):
            # A match on a traced subject is host control flow, exactly
            # like if/while.
            if self._is_tainted(stmt.subject):
                self._flag(
                    stmt,
                    "Python branch on a traced value (lax.cond / jnp.where "
                    "keep it on-device)",
                )
            self._check_expr(stmt.subject)
            for case in stmt.cases:
                self.check_body(case.body)
            return
        # Generic fallback — no statement type may escape the scan: every
        # nested statement list is checked as a body, every expression
        # field is checked for host effects (Return/Expr/Raise/Delete/
        # Global/... all land here).
        for _field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._check_expr(value)
            elif isinstance(value, list):
                stmts = [v for v in value if isinstance(v, ast.stmt)]
                if stmts:
                    self.check_body(stmts)
                for v in value:
                    if isinstance(v, ast.expr):
                        self._check_expr(v)


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files.values():
        for kernel, statics in scan_kernels(sf):
            checker = _KernelChecker(sf, kernel, statics)
            checker.check_body(kernel.body)
            findings.extend(checker.findings)
    return findings
