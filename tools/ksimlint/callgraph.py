"""Interprocedural layer: the project call graph + per-function summaries.

Round 11 built ksimlint as five intra-procedural, annotation-driven
rules; rounds 12-17 grew a genuinely concurrent system (the job worker
pool, watchdogged dispatch workers, SSE handler threads, the journal
compaction path, the process-wide CompileCache) whose cross-lock
acquisition orders and thread-role boundaries no per-function walk can
see.  This module is the shared substrate for the three interprocedural
rules (lock-order, thread-role, exception-flow): a module-qualified
call graph over the existing ``Project`` ASTs plus, per function,

- lexically-held lock-domain sets at every call and acquisition site
  (the RacerD-style lock-set summary; Blackshear et al., "RacerD:
  compositional static race detection", OOPSLA 2018),
- transitive may-acquire sets (the lock-order graph's edge source;
  Naik et al., "Effective static deadlock detection", ICSE 2009),
- raise/Thread-target/role facts for the exception-flow and
  thread-role rules.

Everything here is stdlib-only and AST-derived (the analyzer's own
import-boundary contract).  Resolution is deliberately CONSERVATIVE on
dynamic dispatch: a receiver whose class cannot be pinned through the
local type environment (parameter annotations, ``x = ClassName(...)``,
``x: ClassName``, ``self.attr`` types from ``__init__``, typed
dict-container element access) resolves to NOTHING rather than to
every same-named method in the tree — a false ``list.append ->
JobJournal.append`` edge would invent deadlocks, while a missed edge
is a documented soundness limit (docs/lint.md "Soundness limits").

Lock domains are spelled ``ClassName.attr`` for instance locks
(``Job._cond``) and ``modulestem.NAME`` for module-global locks
(``replay._PREWARM_LOCK``); a domain exists where ``threading.Lock /
RLock / Condition`` is constructed.  ``with x.cm():`` over a project
``@contextmanager`` acquires whatever that generator lexically holds
at its ``yield`` (how ``ClusterStore.transaction`` hands its RLock to
the caller's block).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.ksimlint.core import Project, SourceFile

__all__ = ["CallGraph", "FuncInfo", "ClassInfo"]

_FUNC = (ast.FunctionDef, ast.AsyncFunctionDef)

#: threading constructors that create a lock domain.
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

ROLE_RE = re.compile(r"ksimlint:\s*thread-role\(([a-z-]+)\)")
WORKER_RE = re.compile(r"ksimlint:\s*worker-thread")
#: lock-held now also accepts qualified domains (``Class.attr`` /
#: ``modulestem.NAME``) for callbacks invoked with a FOREIGN lock held
#: (JobManager._journal_records runs under the journal lock).
LOCK_HELD_RE = re.compile(r"ksimlint:\s*lock-held\(([A-Za-z_][\w.]*)\)")
LOCK_ORDER_RE = re.compile(r"ksimlint:\s*lock-order\(([^)]+)\)")

#: Broad handler spellings (shield EVERYTHING, including RunCancelled).
BROAD = frozenset({"Exception", "BaseException", "*bare*"})


def _name_tail(expr: ast.expr) -> "str | None":
    """``Name`` or dotted-``Attribute`` tail (``errors.RunCancelled`` ->
    ``RunCancelled``); None for anything else."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _def_directive(sf: SourceFile, fn, pattern: re.Pattern):
    end = fn.body[0].lineno - 1 if fn.body else fn.lineno
    return sf.directive_in_range(fn.lineno, max(fn.lineno, end), pattern)


@dataclass
class ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    bases: tuple[str, ...] = ()
    methods: dict = field(default_factory=dict)  # name -> FuncInfo
    lock_attrs: dict = field(default_factory=dict)  # attr -> Lock|RLock|Condition
    # attr -> ("cls", class name) | ("map", value class name): the
    # __init__-derived receiver types (``self._journal = JobJournal(p)``,
    # ``self._jobs: "OrderedDict[str, Job]"``).
    attr_types: dict = field(default_factory=dict)

    @property
    def key(self) -> tuple:
        return (self.rel, self.name)


@dataclass
class FuncInfo:
    key: str  # "rel::Qual.Path"
    sf: SourceFile
    node: object  # FunctionDef | AsyncFunctionDef
    cls: "ClassInfo | None"
    parent: "FuncInfo | None" = None
    nested: dict = field(default_factory=dict)  # name -> FuncInfo
    role: "str | None" = None  # thread-role annotation (or worker-thread)
    is_ctxmanager: bool = False

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def rel(self) -> str:
        return self.sf.rel

    def display(self) -> str:
        return self.key.split("::", 1)[1]


@dataclass
class ModuleInfo:
    sf: SourceFile
    stem: str
    classes: dict = field(default_factory=dict)  # name -> ClassInfo
    functions: dict = field(default_factory=dict)  # name -> FuncInfo
    imports: dict = field(default_factory=dict)  # local -> (module rel/dotted, orig|None)
    global_types: dict = field(default_factory=dict)  # NAME -> class name (TRACE -> TracePlane)
    global_locks: dict = field(default_factory=dict)  # NAME -> lock kind


@dataclass(frozen=True)
class Acq:
    """One lock-domain acquisition: ``domain`` acquired at ``line``
    while ``held`` (possibly empty) was already held."""

    domain: str
    line: int
    held: frozenset


@dataclass(frozen=True)
class CallSite:
    callee: str  # FuncInfo.key
    line: int
    end_line: int
    held: frozenset  # domains lexically held at the call
    # Innermost-first enclosing-try shields: (id, absorbed-names).  A
    # name in ``absorbed`` is caught by a handler with NO bare raise.
    shields: tuple = ()
    same_receiver: bool = False  # self.m() / nested-def / same-module f()


@dataclass(frozen=True)
class RaiseSite:
    exc: str  # exception class name tail ("" for bare re-raise)
    line: int
    shields: tuple = ()


@dataclass(frozen=True)
class ThreadSite:
    rel: str
    line: int
    target: "str | None"  # FuncInfo.key when resolved
    expr: str  # source text of the target expression
    resolved_external: bool  # True when the target is known non-project


class CallGraph:
    """Built once per Project (``core.Project.callgraph()`` memoizes)."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[tuple, ClassInfo] = {}
        self.by_class_name: dict[str, list[ClassInfo]] = {}
        self.lock_kinds: dict[str, str] = {}  # domain -> Lock|RLock|Condition
        self.acquires: dict[str, list[Acq]] = {}
        self.calls: dict[str, list[CallSite]] = {}
        self.raises: dict[str, list[RaiseSite]] = {}
        self.thread_sites: list[ThreadSite] = []
        self.yield_held: dict[str, frozenset] = {}
        self.annotated_held: dict[str, frozenset] = {}
        self.may_acquire: dict[str, frozenset] = {}
        self.blessed_edges: dict[tuple, tuple] = {}  # (A, B) -> (rel, line)
        self._local_types_cache: dict[str, dict] = {}
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        for sf in self.project.files.values():
            self._index_module(sf)
        # Two-phase held walk: pass 1 ignores contextmanager Withs so
        # yield-held sets exist; pass 2 resolves them.  Pass 1 only
        # needs to cover @contextmanager generators — they are the only
        # functions whose yield-held set is ever consulted.
        for fi in self.functions.values():
            if fi.is_ctxmanager:
                self._walk_function(fi, 1)
        for fi in self.functions.values():
            self._walk_function(fi, 2)
        self._fixpoint_may_acquire()
        self._collect_blessed()

    def _index_module(self, sf: SourceFile) -> None:
        stem = sf.rel.rsplit("/", 1)[-1][: -len(".py")]
        mi = ModuleInfo(sf=sf, stem=stem)
        self.modules[sf.rel] = mi
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._index_import(mi, stmt)
            elif isinstance(stmt, ast.ClassDef):
                self._index_class(mi, stmt)
            elif isinstance(stmt, _FUNC):
                self._index_func(mi, stmt, cls=None, parent=None)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._index_global_assign(mi, stmt)

    def _index_import(self, mi: ModuleInfo, stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                local = alias.asname or alias.name.split(".")[0]
                mi.imports[local] = (alias.name, None)
        else:
            if stmt.level:  # relative imports are not used in this tree
                return
            mod = stmt.module or ""
            for alias in stmt.names:
                local = alias.asname or alias.name
                mi.imports[local] = (mod, alias.name)

    def _index_class(self, mi: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(
            name=node.name,
            rel=mi.sf.rel,
            node=node,
            bases=tuple(ast.unparse(b) for b in node.bases),
        )
        mi.classes[node.name] = ci
        self.classes[ci.key] = ci
        self.by_class_name.setdefault(node.name, []).append(ci)
        for stmt in node.body:
            if isinstance(stmt, _FUNC):
                fi = self._index_func(mi, stmt, cls=ci, parent=None)
                ci.methods[stmt.name] = fi
                if stmt.name == "__init__":
                    self._index_init(mi, ci, stmt)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                for tgt in self._targets(stmt):
                    if isinstance(tgt, ast.Name):
                        kind = self._lock_ctor(stmt)
                        if kind:
                            ci.lock_attrs[tgt.id] = kind

    def _index_init(self, mi: ModuleInfo, ci: ClassInfo, fn) -> None:
        # ``self.store = store`` where the __init__ PARAM is annotated:
        # the dominant constructor idiom in this tree (ScenarioRunner's
        # ``store: ClusterStore | None``).
        param_types: dict[str, tuple] = {}
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                typ = self._parse_type_expr(a.annotation)
                if typ is not None:
                    param_types[a.arg] = typ
        for sub in ast.walk(fn):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            for tgt in self._targets(sub):
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                kind = self._lock_ctor(sub)
                if kind:
                    ci.lock_attrs[tgt.attr] = kind
                    continue
                typ = None
                if isinstance(sub, ast.AnnAssign):
                    typ = self._parse_type_expr(sub.annotation)
                value = getattr(sub, "value", None)
                if typ is None and value is not None:
                    typ = self._value_type_name(value)
                if typ is None and isinstance(value, ast.Name):
                    typ = param_types.get(value.id)
                if typ is not None:
                    ci.attr_types.setdefault(tgt.attr, typ)

    def _index_func(self, mi, node, cls, parent) -> FuncInfo:
        if parent is not None:
            qual = f"{parent.display()}.{node.name}"
        elif cls is not None:
            qual = f"{cls.name}.{node.name}"
        else:
            qual = node.name
        fi = FuncInfo(
            key=f"{mi.sf.rel}::{qual}", sf=mi.sf, node=node, cls=cls, parent=parent
        )
        m = _def_directive(mi.sf, node, ROLE_RE)
        if m:
            fi.role = m.group(1)
        elif _def_directive(mi.sf, node, WORKER_RE):
            fi.role = "dispatch-worker"
        fi.is_ctxmanager = any(
            _name_tail(d) == "contextmanager"
            for d in node.decorator_list
            if isinstance(d, (ast.Name, ast.Attribute))
        )
        self.functions[fi.key] = fi
        if cls is None and parent is None:
            mi.functions[node.name] = fi
        m = _def_directive(mi.sf, node, LOCK_HELD_RE)
        if m:
            self.annotated_held[fi.key] = frozenset(
                {self._domain_from_annotation(fi, m.group(1))}
            )
        for stmt in self._direct_nested(node):
            fi.nested[stmt.name] = self._index_func(mi, stmt, cls=cls, parent=fi)
        return fi

    @staticmethod
    def _direct_nested(node):
        """DIRECTLY nested defs of ``node`` in one pass: descend child
        nodes but never INTO a nested def (deeper defs belong to it and
        index through the recursion in ``_index_func``)."""
        out = []
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, _FUNC):
                out.append(n)
                continue
            if isinstance(n, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return sorted(out, key=lambda d: d.lineno)

    def _index_global_assign(self, mi: ModuleInfo, stmt) -> None:
        kind = self._lock_ctor(stmt)
        for tgt in self._targets(stmt):
            if not isinstance(tgt, ast.Name):
                continue
            if kind:
                mi.global_locks[tgt.id] = kind
                self.lock_kinds[f"{mi.stem}.{tgt.id}"] = kind
            elif getattr(stmt, "value", None) is not None:
                typ = self._value_type_name(stmt.value)
                if typ is not None:
                    mi.global_types[tgt.id] = typ

    @staticmethod
    def _targets(stmt):
        if isinstance(stmt, ast.Assign):
            return stmt.targets
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            return [stmt.target]
        return []

    @staticmethod
    def _lock_ctor(stmt) -> "str | None":
        value = getattr(stmt, "value", None)
        if isinstance(value, ast.Call):
            tail = _name_tail(value.func)
            return _LOCK_CTORS.get(tail or "")
        return None

    @staticmethod
    def _value_type_name(value: ast.expr) -> "str | None":
        """``X = ClassName(...)`` -> "ClassName" (validated against the
        project's classes at resolution time, not here)."""
        if isinstance(value, ast.Call):
            tail = _name_tail(value.func)
            if tail and tail[:1].isupper():
                return ("cls", tail)
        return None

    def _parse_type_expr(self, ann: ast.expr) -> "tuple | None":
        """A (possibly string) annotation -> ("cls", Name) for a plain /
        Optional class, ("map", ValueName) for dict-like containers."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            # "JobJournal | None": take the non-None side.
            for side in (ann.left, ann.right):
                got = self._parse_type_expr(side)
                if got is not None:
                    return got
            return None
        if isinstance(ann, ast.Subscript):
            base = _name_tail(ann.value) or ""
            if base in ("dict", "Dict", "OrderedDict", "defaultdict"):
                sl = ann.slice
                if isinstance(sl, ast.Tuple) and sl.elts:
                    got = self._parse_type_expr(sl.elts[-1])
                    if got is not None and got[0] == "cls":
                        return ("map", got[1])
            if base in ("Optional",):
                return self._parse_type_expr(ann.slice)
            return None
        tail = _name_tail(ann)
        if tail and tail[:1].isupper() and tail != "None":
            return ("cls", tail)
        return None

    # -- name / receiver resolution --------------------------------------

    def _resolve_class(self, mi: ModuleInfo, name: str) -> "ClassInfo | None":
        if name in mi.classes:
            return mi.classes[name]
        imp = mi.imports.get(name)
        if imp:
            mod, orig = imp
            target = self.modules.get(self._module_rel(mod))
            if target is not None:
                return target.classes.get(orig or name)
        return None

    def _module_rel(self, dotted: str) -> str:
        rel = dotted.replace(".", "/") + ".py"
        if rel in self.modules:
            return rel
        return dotted.replace(".", "/") + "/__init__.py"

    def _resolve_module_func(self, mi: ModuleInfo, name: str) -> "FuncInfo | None":
        if name in mi.functions:
            return mi.functions[name]
        imp = mi.imports.get(name)
        if imp:
            mod, orig = imp
            target = self.modules.get(self._module_rel(mod))
            if target is not None:
                return target.functions.get(orig or name)
        return None

    def _method_on(self, ci: "ClassInfo | None", name: str) -> "FuncInfo | None":
        """Method lookup through project base classes."""
        seen = set()
        while ci is not None and ci.key not in seen:
            seen.add(ci.key)
            if name in ci.methods:
                return ci.methods[name]
            nxt = None
            for base in ci.bases:
                got = self._resolve_class(self.modules[ci.rel], base.split(".")[-1])
                if got is not None:
                    nxt = got
                    break
            ci = nxt
        return None

    def _lock_attr_on(self, ci: "ClassInfo | None", attr: str) -> "str | None":
        """The (class, kind) domain for ``<ci instance>.<attr>`` when the
        attr is a lock constructed by ci or a project base."""
        seen = set()
        while ci is not None and ci.key not in seen:
            seen.add(ci.key)
            if attr in ci.lock_attrs:
                domain = f"{ci.name}.{attr}"
                self.lock_kinds.setdefault(domain, ci.lock_attrs[attr])
                return domain
            nxt = None
            for base in ci.bases:
                got = self._resolve_class(self.modules[ci.rel], base.split(".")[-1])
                if got is not None:
                    nxt = got
                    break
            ci = nxt
        return None

    def _attr_type(self, ci: "ClassInfo | None", attr: str) -> "tuple | None":
        seen = set()
        while ci is not None and ci.key not in seen:
            seen.add(ci.key)
            if attr in ci.attr_types:
                return ci.attr_types[attr]
            nxt = None
            for base in ci.bases:
                got = self._resolve_class(self.modules[ci.rel], base.split(".")[-1])
                if got is not None:
                    nxt = got
                    break
            ci = nxt
        return None

    def _domain_from_annotation(self, fi: FuncInfo, name: str) -> str:
        """``lock-held(X)``: bare attr names resolve against the
        enclosing class; qualified ``Class.attr`` / ``modulestem.NAME``
        pass through as spelled."""
        if "." in name:
            return name
        if fi.cls is not None:
            domain = self._lock_attr_on(fi.cls, name)
            if domain:
                return domain
            return f"{fi.cls.name}.{name}"
        mi = self.modules[fi.rel]
        if name in mi.global_locks:
            return f"{mi.stem}.{name}"
        return name

    # -- the per-function walk -------------------------------------------

    def _local_types(self, fi: FuncInfo) -> dict:
        """Flow-insensitive local name -> type env for one function
        (memoized: the two walk phases and resolve_call share it)."""
        cached = self._local_types_cache.get(fi.key)
        if cached is not None:
            return cached
        mi = self.modules[fi.rel]
        env: dict[str, tuple] = {}
        args = fi.node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            if a.annotation is not None:
                typ = self._parse_type_expr(a.annotation)
                if typ is not None:
                    env.setdefault(a.arg, typ)
        for sub in ast.walk(fi.node):
            if isinstance(sub, _FUNC) and sub is not fi.node:
                continue
            if isinstance(sub, ast.AnnAssign) and isinstance(sub.target, ast.Name):
                typ = self._parse_type_expr(sub.annotation)
                if typ is not None:
                    env.setdefault(sub.target.id, typ)
            elif isinstance(sub, ast.Assign):
                typ = self._expr_type(fi, mi, sub.value, env)
                if typ is not None:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            env.setdefault(tgt.id, typ)
            elif isinstance(sub, ast.For) and isinstance(sub.target, ast.Name):
                typ = self._expr_type(fi, mi, sub.iter, env)
                if typ is not None and typ[0] == "iter-cls":
                    env.setdefault(sub.target.id, ("cls", typ[1]))
        self._local_types_cache[fi.key] = env
        return env

    def _expr_type(self, fi, mi, expr, env) -> "tuple | None":
        """("cls", Name) receiver types, plus ("map"/"iter-cls", Name)
        intermediates for dict element access."""
        if isinstance(expr, ast.Name):
            got = env.get(expr.id)
            if got is not None:
                return got
            g = mi.global_types.get(expr.id)
            if g is not None:
                return g
            imp = mi.imports.get(expr.id)
            if imp:
                target = self.modules.get(self._module_rel(imp[0]))
                if target is not None:
                    g = target.global_types.get(imp[1] or expr.id)
                    if g is not None:
                        return g
            return None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return self._attr_type(fi.cls, expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            base = self._expr_type(fi, mi, expr.value, env)
            if base is not None and base[0] == "map":
                return ("cls", base[1])
            return None
        if isinstance(expr, ast.Call):
            tail = _name_tail(expr.func)
            if tail is None:
                return None
            ci = (
                self._resolve_class(mi, tail)
                if isinstance(expr.func, ast.Name)
                else None
            )
            if ci is not None:
                return ("cls", ci.name)
            if isinstance(expr.func, ast.Attribute) and tail in ("get", "pop"):
                base = self._expr_type(fi, mi, expr.func.value, env)
                if base is not None and base[0] == "map":
                    return ("cls", base[1])
            if isinstance(expr.func, ast.Attribute) and tail == "values":
                base = self._expr_type(fi, mi, expr.func.value, env)
                if base is not None and base[0] == "map":
                    return ("iter-cls", base[1])
        return None

    def resolve_call(self, fi: FuncInfo, call: ast.Call, env=None) -> "FuncInfo | None":
        """The single project callee of ``call`` inside ``fi``, or None
        (unresolvable / external — the conservative default)."""
        mi = self.modules[fi.rel]
        if env is None:
            env = self._local_types(fi)
        f = call.func
        if isinstance(f, ast.Name):
            scope = fi
            while scope is not None:
                if f.id in scope.nested:
                    return scope.nested[f.id]
                scope = scope.parent
            ci = self._resolve_class(mi, f.id)
            if ci is not None:
                return self._method_on(ci, "__init__")
            return self._resolve_module_func(mi, f.id)
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls") and fi.cls:
                return self._method_on(fi.cls, f.attr)
            if isinstance(recv, ast.Name):
                imp = mi.imports.get(recv.id)
                if imp and imp[1] is None:
                    target = self.modules.get(self._module_rel(imp[0]))
                    if target is not None:
                        got = target.functions.get(f.attr)
                        if got is not None:
                            return got
                        ci = target.classes.get(f.attr)
                        if ci is not None:
                            return self._method_on(ci, "__init__")
                    return None
            typ = self._expr_type(fi, mi, recv, env)
            if typ is not None and typ[0] == "cls":
                ci = self._resolve_class(mi, typ[1])
                if ci is None:
                    for cand in self.by_class_name.get(typ[1], []):
                        ci = cand
                        break
                if ci is not None:
                    return self._method_on(ci, f.attr)
        return None

    def _with_domains(self, fi: FuncInfo, item: ast.withitem, env, phase: int):
        """Domains acquired by one with-item context expression."""
        expr = item.context_expr
        mi = self.modules[fi.rel]
        if isinstance(expr, ast.Name):
            if expr.id in mi.global_locks:
                return [f"{mi.stem}.{expr.id}"]
            imp = mi.imports.get(expr.id)
            if imp:
                target = self.modules.get(self._module_rel(imp[0]))
                if target is not None and (imp[1] or expr.id) in target.global_locks:
                    return [f"{target.stem}.{imp[1] or expr.id}"]
            return []
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            if isinstance(recv, ast.Name) and recv.id == "self" and fi.cls:
                domain = self._lock_attr_on(fi.cls, expr.attr)
                return [domain] if domain else []
            typ = self._expr_type(fi, mi, recv, env)
            if typ is not None and typ[0] == "cls":
                ci = self._resolve_class(mi, typ[1])
                if ci is None:
                    cands = self.by_class_name.get(typ[1], [])
                    ci = cands[0] if cands else None
                domain = self._lock_attr_on(ci, expr.attr) if ci else None
                return [domain] if domain else []
            return []
        if phase == 2 and isinstance(expr, ast.Call):
            callee = self.resolve_call(fi, expr, env)
            if callee is not None and callee.is_ctxmanager:
                return sorted(self.yield_held.get(callee.key, frozenset()))
        return []

    def _walk_function(self, fi: FuncInfo, phase: int) -> None:
        env = self._local_types(fi)
        acquires: list[Acq] = []
        calls: list[CallSite] = []
        raises: list[RaiseSite] = []
        yheld: set[str] = set()
        graph = self

        init_held = self.annotated_held.get(fi.key, frozenset())

        def handler_names(try_node) -> frozenset:
            absorbed = set()
            for h in try_node.handlers:
                reraises = any(
                    isinstance(s, ast.Raise) and s.exc is None
                    for s in ast.walk(h)
                )
                if reraises:
                    continue
                if h.type is None:
                    absorbed.add("*bare*")
                elif isinstance(h.type, ast.Tuple):
                    absorbed.update(
                        _name_tail(e) or "?" for e in h.type.elts
                    )
                else:
                    absorbed.add(_name_tail(h.type) or "?")
            return frozenset(absorbed)

        def same_receiver(call: ast.Call) -> bool:
            f = call.func
            if isinstance(f, ast.Name):
                return True  # nested def or same-module function
            return (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            )

        def visit(node, held: frozenset, shields: tuple) -> None:
            if isinstance(node, _FUNC) or isinstance(node, ast.Lambda):
                return  # nested scopes are separate functions
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired: list[str] = []
                for item in node.items:
                    visit_expr(item.context_expr, held, shields)
                    for domain in graph._with_domains(fi, item, env, phase):
                        acquired.append(domain)
                        acquires.append(Acq(domain, item.context_expr.lineno, held))
                inner = held | frozenset(acquired)
                for stmt in node.body:
                    visit(stmt, inner, shields)
                return
            if isinstance(node, ast.Try):
                shield = (id(node), handler_names(node))
                for stmt in node.body:
                    visit(stmt, held, (shield,) + shields)
                for stmt in node.orelse:
                    visit(stmt, held, shields)
                for h in node.handlers:
                    for stmt in h.body:
                        visit(stmt, held, shields)
                for stmt in node.finalbody:
                    visit(stmt, held, shields)
                return
            if isinstance(node, ast.Raise):
                if node.exc is None:
                    raises.append(RaiseSite("", node.lineno, shields))
                else:
                    tail = _name_tail(
                        node.exc.func if isinstance(node.exc, ast.Call) else node.exc
                    )
                    if tail:
                        raises.append(RaiseSite(tail, node.lineno, shields))
                    if isinstance(node.exc, ast.Call):
                        visit_expr(node.exc, held, shields)
                return
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Yield):
                yheld.update(held)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    visit_expr(child, held, shields)
                else:
                    visit(child, held, shields)

        def visit_expr(expr, held: frozenset, shields: tuple) -> None:
            for node in ast.walk(expr):
                if isinstance(node, (ast.Lambda,)) or isinstance(node, _FUNC):
                    continue
                if isinstance(node, ast.Yield):
                    yheld.update(held)
                if not isinstance(node, ast.Call):
                    continue
                graph._note_thread_site(fi, node, env)
                callee = graph.resolve_call(fi, node, env)
                if callee is not None:
                    calls.append(
                        CallSite(
                            callee.key,
                            node.lineno,
                            getattr(node, "end_lineno", node.lineno),
                            held,
                            shields,
                            same_receiver(node),
                        )
                    )

        for stmt in fi.node.body:
            visit(stmt, init_held, ())
        if phase == 1:
            self.yield_held[fi.key] = frozenset(yheld)
        else:
            self.acquires[fi.key] = acquires
            self.calls[fi.key] = calls
            self.raises[fi.key] = raises

    def _note_thread_site(self, fi: FuncInfo, call: ast.Call, env) -> None:
        """Record ``threading.Thread(target=X)`` / ``pool.submit(X, ..)``
        sites (phase-independent; duplicates are deduped at the end)."""
        tail = _name_tail(call.func)
        target_expr = None
        if tail == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif tail == "submit" and isinstance(call.func, ast.Attribute) and call.args:
            target_expr = call.args[0]
        if target_expr is None:
            return
        resolved: "str | None" = None
        external = False
        fake_call = ast.Call(func=target_expr, args=[], keywords=[])
        ast.copy_location(fake_call, call)
        callee = None
        try:
            callee = self.resolve_call(fi, fake_call, env)
        except Exception:
            callee = None
        if callee is not None:
            resolved = callee.key
        else:
            # self.<m> that did not resolve within project classes is an
            # inherited external method (serve_forever) — known-external.
            external = True
        site = ThreadSite(
            fi.rel, call.lineno, resolved, ast.unparse(target_expr), external
        )
        if site not in self.thread_sites:
            self.thread_sites.append(site)

    # -- summaries --------------------------------------------------------

    def _fixpoint_may_acquire(self) -> None:
        may: dict[str, set] = {
            key: {a.domain for a in acqs} for key, acqs in self.acquires.items()
        }
        for key in self.functions:
            may.setdefault(key, set())
        changed = True
        while changed:
            changed = False
            for key, sites in self.calls.items():
                mine = may[key]
                before = len(mine)
                for site in sites:
                    mine |= may.get(site.callee, set())
                if len(mine) != before:
                    changed = True
        self.may_acquire = {k: frozenset(v) for k, v in may.items()}

    def _collect_blessed(self) -> None:
        """``# ksimlint: lock-order(A<B[<C...])`` declarations anywhere
        in the tree (chains expand to adjacent pairs)."""
        for sf in self.project.files.values():
            for line, comment in sf.comments.items():
                m = LOCK_ORDER_RE.search(comment)
                if not m:
                    continue
                parts = [p.strip() for p in m.group(1).split("<")]
                for a, b in zip(parts, parts[1:]):
                    if a and b:
                        self.blessed_edges.setdefault((a, b), (sf.rel, line))

    # -- derived facts shared by the rules --------------------------------

    def observed_edges(self) -> dict:
        """(A, B) -> list of witness (rel, line, description): every
        second-lock acquisition while a first is held, both direct and
        through the transitive may-acquire of a callee."""
        edges: dict[tuple, list] = {}

        def add(a, b, rel, line, desc):
            if a == b:
                return
            edges.setdefault((a, b), []).append((rel, line, desc))

        for key, acqs in self.acquires.items():
            fi = self.functions[key]
            for acq in acqs:
                for a in acq.held:
                    add(
                        a,
                        acq.domain,
                        fi.rel,
                        acq.line,
                        f"{fi.display()} acquires {acq.domain} while holding {a}",
                    )
        for key, sites in self.calls.items():
            fi = self.functions[key]
            for site in sites:
                if not site.held:
                    continue
                for b in self.may_acquire.get(site.callee, frozenset()):
                    for a in site.held:
                        callee = self.functions[site.callee]
                        add(
                            a,
                            b,
                            fi.rel,
                            site.line,
                            f"{fi.display()} calls {callee.display()} "
                            f"(may acquire {b}) while holding {a}",
                        )
        for ws in edges.values():
            ws.sort(key=lambda w: (w[0], w[1]))
        return edges

    def reentrant_acquisitions(self) -> list:
        """Direct nested acquisitions of one NON-reentrant domain — a
        guaranteed self-deadlock (RLock domains are exempt)."""
        out = []
        for key, acqs in self.acquires.items():
            fi = self.functions[key]
            for acq in acqs:
                if (
                    acq.domain in acq.held
                    and self.lock_kinds.get(acq.domain) != "RLock"
                ):
                    out.append((fi, acq))
        return out

    def roots_with_role(self, roles: frozenset) -> list:
        return [fi for fi in self.functions.values() if fi.role in roles]

    def reachable_same_receiver(self, roots) -> dict:
        """FuncInfo.key -> (root FuncInfo, via FuncInfo) for everything
        reachable from ``roots`` along same-receiver call edges (the
        thread-role propagation relation)."""
        out: dict[str, tuple] = {}
        stack = [(fi, fi, fi) for fi in roots]
        while stack:
            root, via, fi = stack.pop()
            if fi.key in out:
                continue
            out[fi.key] = (root, via)
            for site in self.calls.get(fi.key, ()):
                if not site.same_receiver:
                    continue
                callee = self.functions.get(site.callee)
                if callee is not None and callee.key not in out:
                    stack.append((root, fi, callee))
        return out

    def may_raise(self, exc_name: str) -> frozenset:
        """Keys of functions from which ``exc_name`` may ESCAPE: a raise
        (or a call to an escaping callee) not shielded by an enclosing
        handler that absorbs it (explicitly by name, or a broad handler
        — the broad case is exactly what the exception-flow rule then
        inspects at the absorbing site)."""

        def shielded(shields: tuple) -> bool:
            for _tid, absorbed in shields:
                if exc_name in absorbed or absorbed & BROAD:
                    return True
            return False

        escaping: set[str] = set()
        for key, rss in self.raises.items():
            for rs in rss:
                if rs.exc == exc_name and not shielded(rs.shields):
                    escaping.add(key)
                    break
        changed = True
        while changed:
            changed = False
            for key, sites in self.calls.items():
                if key in escaping:
                    continue
                for site in sites:
                    if site.callee in escaping and not shielded(site.shields):
                        escaping.add(key)
                        changed = True
                        break
        return frozenset(escaping)
