"""ksimlint core: source loading, comment directives, the rule runner.

Stdlib-only BY CONTRACT (enforced by ksimlint's own import-boundary
rule): the analyzer runs in the sanitized environment, in bench
children's parents, and in CI shells where jax backend init may be
wedged — it must never import jax, numpy, or ksim_tpu itself.  All
facts about the codebase are extracted from Python ASTs and the token
stream, never by importing the code under analysis.

Vocabulary (docs/lint.md has the full catalogue):

- A **rule** is a module under ``tools/ksimlint/rules`` exposing
  ``RULE`` (its kebab-case name) and ``check(project) -> [Finding]``.
- A **directive** is a structured comment the rules read:
  ``# guarded-by: <lock>`` on an attribute's initializing assignment,
  ``# ksimlint: lock-held(<lock>)`` / ``# ksimlint: worker-thread`` on
  a ``def`` line, and ``# ksimlint: disable=<rule>[,<rule>...]`` to
  suppress findings on that line (or, from a comment-only line, on the
  line below it).
- A **finding** is one contract violation at one source line; the run
  fails (exit 1) on any finding that is not suppressed.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, replace

#: What ``make lint`` (and the no-argument CLI) analyzes.  tests/ is
#: deliberately out of scope: fixtures there contain SEEDED violations.
DEFAULT_TARGETS: tuple[str, ...] = ("ksim_tpu", "bench.py", "tools")

_DISABLE_RE = re.compile(r"ksimlint:\s*disable=([\w,-]+)")


@dataclass(frozen=True)
class Finding:
    """One contract violation at one source line."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
        }


def _disabled_rules(comment: str) -> frozenset[str]:
    m = _DISABLE_RE.search(comment)
    if not m:
        return frozenset()
    return frozenset(r for r in m.group(1).split(",") if r)


class SourceFile:
    """One parsed source file: AST + per-line comment map.

    ``comments`` maps line number -> comment text (with the ``#``);
    ``comment_only`` holds lines where the comment is the whole line,
    so a directive there can apply to the statement below it.
    """

    __slots__ = ("path", "rel", "text", "tree", "comments", "comment_only")

    def __init__(self, path: str, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        self.comments: dict[int, str] = {}
        self.comment_only: set[int] = set()
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                line = tok.start[0]
                self.comments[line] = tok.string
                if tok.line[: tok.start[1]].strip() == "":
                    self.comment_only.add(line)

    def disabled_at(self, line: int) -> frozenset[str]:
        """Rules suppressed for findings on ``line``: a disable comment
        on the line itself, or on a comment-only line directly above."""
        out = _disabled_rules(self.comments.get(line, ""))
        if line - 1 in self.comment_only:
            out |= _disabled_rules(self.comments[line - 1])
        return out

    def directive_in_range(self, start: int, end: int, pattern: re.Pattern):
        """First regex match of ``pattern`` over the comments on lines
        ``start..end`` inclusive (rules use this to read annotations
        anywhere inside a statement's line span)."""
        for ln in range(start, end + 1):
            c = self.comments.get(ln)
            if c:
                m = pattern.search(c)
                if m:
                    return m
        return None


class Project:
    """The analyzed tree: repo root + the loaded source files.
    ``targets`` records what was requested, so rules whose cross-file
    directions only make sense over the full default tree (env-contract
    dead rows) can tell a partial run apart."""

    def __init__(
        self,
        root: str,
        files: dict[str, SourceFile],
        targets: tuple[str, ...] = DEFAULT_TARGETS,
    ) -> None:
        self.root = root
        self.files = files
        self.targets = targets
        self._callgraph = None

    def callgraph(self):
        """The interprocedural layer (tools/ksimlint/callgraph.py),
        built lazily ONCE per Project and shared by every rule that asks
        — the lock-order, thread-role and exception-flow rules all walk
        the same call graph instead of re-deriving it per rule."""
        if self._callgraph is None:
            from tools.ksimlint.callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph

    @classmethod
    def load(cls, root: str, targets: tuple[str, ...] = DEFAULT_TARGETS) -> "Project":
        root = os.path.abspath(root)
        files: dict[str, SourceFile] = {}

        def add(path: str) -> None:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                files[rel] = SourceFile(path, rel, f.read())

        for target in targets:
            path = os.path.join(root, target)
            if os.path.isfile(path):
                add(path)
            elif os.path.isdir(path):
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = sorted(
                        d
                        for d in dirnames
                        if d != "__pycache__" and not d.startswith(".")
                    )
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            add(os.path.join(dirpath, fn))
            else:
                # A typo'd target silently scanning nothing would make
                # the gate vacuously green — refuse loudly (exit 2).
                raise OSError(f"lint target not found: {path}")
        return cls(root, dict(sorted(files.items())), tuple(targets))

    def covers_default_targets(self) -> bool:
        """True when the run includes the whole default tree (the only
        scope where \"documented but unused\" style cross-file checks
        are meaningful)."""
        return all(t in self.targets for t in DEFAULT_TARGETS)

    def read_text(self, rel: str) -> "str | None":
        """Non-Python project file (e.g. docs/env.md); None if absent."""
        path = os.path.join(self.root, rel.replace("/", os.sep))
        if not os.path.isfile(path):
            return None
        with open(path, encoding="utf-8") as f:
            return f.read()


def mark_suppressed(project: Project, findings: list[Finding]) -> list[Finding]:
    """Apply inline suppressions; returns findings sorted by location."""
    out: list[Finding] = []
    for f in findings:
        sf = project.files.get(f.path)
        if sf is not None:
            disabled = sf.disabled_at(f.line)
            if f.rule in disabled or "all" in disabled:
                f = replace(f, suppressed=True)
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def run(
    root: str,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
    rules: "tuple[str, ...] | None" = None,
) -> list[Finding]:
    """Load the tree and run every (or the selected) rule.  Returns ALL
    findings; callers filter on ``suppressed`` for the exit status."""
    from tools.ksimlint.rules import ALL_RULES

    if rules is not None:
        unknown = sorted(set(rules) - set(ALL_RULES))
        if unknown:
            # A typo'd rule filter running zero rules would be the same
            # vacuously-green gate Project.load refuses for bad targets.
            raise ValueError(
                f"unknown rule(s) {', '.join(unknown)} "
                f"(have: {', '.join(sorted(ALL_RULES))})"
            )
    project = Project.load(root, targets)
    findings: list[Finding] = []
    for name, check in ALL_RULES.items():
        if rules is not None and name not in rules:
            continue
        findings.extend(check(project))
    return mark_suppressed(project, findings)
