"""ksimlint — the repo's AST contract analyzer (docs/lint.md).

Five rules turn this codebase's informal correctness contracts into
machine-checked invariants:

- ``lock-discipline``   ``# guarded-by:`` attributes only touched under
                        their lock (or in ``lock-held`` methods);
                        ``worker-thread`` functions never write driver
                        state.
- ``kernel-purity``     ``@device_kernel`` trace-time bodies stay free
                        of host effects and f32-determinism hazards.
- ``import-boundary``   the stdlib-only surfaces (bench.py parent,
                        obs/faults/errors, this analyzer) never reach
                        jax/numpy at import time.
- ``registry-literals`` every fault-site / span / event / fallback
                        reason literal resolves into its registry.
- ``env-contract``      every ``KSIM_*`` literal is documented in
                        docs/env.md, and vice versa.

Run ``make lint`` or ``python -m tools.ksimlint``; the package is
stdlib-only and safe in any environment (it never imports jax, numpy,
or ksim_tpu — everything is read from source ASTs).
"""

from tools.ksimlint.core import (
    DEFAULT_TARGETS,
    Finding,
    Project,
    SourceFile,
    run,
)

__all__ = ["DEFAULT_TARGETS", "Finding", "Project", "SourceFile", "run"]
