# Namespace for developer tooling (tools.ksimlint et al.).  The scripts
# in this directory (trace_check.py, perf_table.py) are still run as
# plain scripts; the package __init__ only exists so the analyzer is
# importable as ``tools.ksimlint`` from the repo root.
