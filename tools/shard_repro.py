#!/usr/bin/env python
"""Standalone repro for the sharded preemption-scan partial-sum
miscompile behind ``_MIN_SHARD_NODES`` (ksim_tpu/engine/replay.py).

Pure jax + numpy — NO ksim imports — so the program can be filed
upstream as-is.  It distills the segment kernel's victim-search scan
to its partitioner-relevant skeleton:

- a dict carry of ``[N]`` node tensors laid over a 1-D ``tp`` mesh
  axis via committed ``NamedSharding`` inputs (no ``in_shardings``),
- a ``lax.scan`` over pods whose step runs scatter-counted candidate
  discovery, ``top_k`` over node rank keys, and a ``fori_loop``
  lexicographic-min cascade over candidates,
- per-step ``nom``/``sel`` index outputs the scan stacks to ``[q]``
  and ``[q, K]``.

Observed failure mode (docs/churn_floor.md "Sharded replay"): at
shard width ``N // tp < 4`` the partitioner propagates a
``P(None, 'tp')`` sharding onto the POD axis of the stacked outputs
and emits them as per-replica partial sums that no all-reduce folds —
every index value comes back exactly DOUBLED (-1 as -2, node 2 as 4).
N=16 is clean at every width; isolated ``top_k``/``argmin`` never
reproduce it — the scan + scatter + committed-input combination is
load-bearing.

Usage::

    python tools/shard_repro.py               # N=8 tp=4: the hazard
    python tools/shard_repro.py --nodes 16    # control: clean
    python tools/shard_repro.py --matrix      # documented sweep

Exit status: 0 when sharded == solo (no bug on this jax build),
2 on mismatch (bug reproduced) — so CI can pin either expectation.

Status: on CPU jax 0.4.37 (the lock platform) the distilled skeleton
is CLEAN at every width — the doubling was observed through the full
segment kernel, so the trigger involves program scale the skeleton
does not reach.  That is exactly why ``_MIN_SHARD_NODES`` stays an
empirical floor pinned by the in-kernel observation rather than a
bound derived from this repro; when filing upstream, attach this
script (the structural skeleton reviewers can read) PLUS the HLO dump
of an affected full-kernel lower (``XLA_FLAGS=--xla_dump_to=...``
around a width-2 run with the floor guard lifted).
"""

import argparse
import os
import sys

# The repro needs `tp` XLA devices; on a CPU-only host fake them the
# same way the ksim test suite does, BEFORE jax initializes.
_WANT_DEVS = 8
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_WANT_DEVS}"
    ).strip()

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

I32_MAX = np.int32(np.iinfo(np.int32).max)


def build_problem(n_nodes, n_pods, seed):
    """Deterministic host-side fixture: pods bound to nodes with mixed
    priorities, plus per-node rank keys (the live name order)."""
    rng = np.random.RandomState(seed)
    return {
        "valid": np.ones(n_nodes, bool),
        "rank": rng.permutation(n_nodes).astype(np.int32),
        "requested": rng.uniform(1.0, 4.0, n_nodes).astype(np.float32),
        "bound": rng.randint(0, n_nodes, n_pods).astype(np.int32),
        "alive": np.ones(n_pods, bool),
        "prio": rng.randint(0, 5, n_pods).astype(np.int32),
        "imp_rank": rng.permutation(n_pods).astype(np.int32),
        "req": rng.uniform(0.1, 1.0, n_pods).astype(np.float32),
    }


def segment(node, pod, c_max, v_max):
    """The scan: each pod searches for a preemption target against the
    LIVE carry, binds there, and reports (nom, sel victim rows)."""
    N = node["valid"].shape[0]
    Pn = pod["bound"].shape[0]

    def step(live, j):
        prio_j = pod["prio"][j]
        lower = live["alive"] & (live["bound"] >= 0) & (pod["prio"] < prio_j)
        tgtn = jnp.where(lower, live["bound"], N)
        vcnt = jnp.zeros(N, jnp.int32).at[tgtn].add(1, mode="drop")
        examine = (vcnt > 0) & node["valid"]
        keyed = jnp.where(examine, node["rank"], I32_MAX)
        negk, cand = jax.lax.top_k(-keyed, c_max)
        cand_act = negk > -I32_MAX

        def cand_body(i, acc):
            best_key, best_node, best_vic = acc
            n_i = cand[i]
            on_n = lower & (live["bound"] == n_i)
            kv = jnp.where(on_n, pod["imp_rank"], I32_MAX)
            negv, vrows = jax.lax.top_k(-kv, v_max)
            vact = negv > -I32_MAX
            vprio = jnp.where(vact, pod["prio"][vrows], -1)
            key = (
                jnp.max(vprio) * 10000
                + jnp.sum(jnp.where(vact, pod["prio"][vrows], 0)) * 100
                + jnp.sum(vact.astype(jnp.int32))
            )
            better = cand_act[i] & (key < best_key)
            return (
                jnp.where(better, key, best_key),
                jnp.where(better, n_i, best_node),
                jnp.where(better[None], jnp.where(vact, vrows, -1), best_vic),
            )

        best_key, best_node, best_vic = jax.lax.fori_loop(
            0,
            c_max,
            cand_body,
            (jnp.int32(I32_MAX), jnp.int32(-1), jnp.full(v_max, -1, jnp.int32)),
        )
        hit = best_node >= 0
        evict = hit & (live["bound"] == best_node) & lower
        live = {
            "alive": live["alive"] & ~evict,
            "bound": jnp.where(evict, -1, live["bound"]).at[j].set(
                jnp.where(hit, best_node, live["bound"][j])
            ),
            "requested": live["requested"].at[
                jnp.where(hit, best_node, N)
            ].add(pod["req"][j], mode="drop"),
        }
        return live, {"nom": best_node, "sel": best_vic}

    live0 = {
        "alive": pod["alive"],
        "bound": pod["bound"],
        "requested": node["requested"],
    }
    _live, outs = jax.lax.scan(step, live0, jnp.arange(Pn))
    return outs


def run(n_nodes, n_pods, tp, seed, c_max=4, v_max=4):
    """Run solo and tp-sharded; return (nom/sel pairs, match)."""
    prob = build_problem(n_nodes, n_pods, seed)
    node = {k: prob[k] for k in ("valid", "rank", "requested")}
    pod = {k: prob[k] for k in ("bound", "alive", "prio", "imp_rank", "req")}
    fn = jax.jit(segment, static_argnums=(2, 3))

    solo = jax.tree_util.tree_map(
        np.asarray, fn(node, pod, c_max, v_max)
    )

    devs = jax.devices()
    if len(devs) < tp:
        raise SystemExit(f"need {tp} devices, have {len(devs)}")
    mesh = Mesh(np.asarray(devs[:tp]), ("tp",))
    node_s = {
        k: jax.device_put(v, NamedSharding(mesh, P("tp")))
        for k, v in node.items()
    }
    pod_s = {
        k: jax.device_put(v, NamedSharding(mesh, P()))
        for k, v in pod.items()
    }
    shard = jax.tree_util.tree_map(
        np.asarray, fn(node_s, pod_s, c_max, v_max)
    )
    match = all(
        np.array_equal(solo[k], shard[k]) for k in ("nom", "sel")
    )
    return solo, shard, match


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--pods", type=int, default=12)
    ap.add_argument("--tp", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--matrix",
        action="store_true",
        help="run the documented sweep (N=8 tp=2/4/8, N=16 tp=4/8)",
    )
    args = ap.parse_args(argv)

    print(f"jax {jax.__version__} backend={jax.default_backend()}")
    configs = (
        [(8, 2), (8, 4), (8, 8), (16, 4), (16, 8)]
        if args.matrix
        else [(args.nodes, args.tp)]
    )
    bad = False
    for n, tp in configs:
        solo, shard, ok = run(n, args.pods, tp, args.seed)
        width = n // tp
        print(
            f"N={n:3d} tp={tp} width={width}: "
            + ("MATCH" if ok else "MISMATCH (bug reproduced)")
        )
        if not ok:
            bad = True
            print(f"  solo  nom: {solo['nom']}")
            print(f"  shard nom: {shard['nom']}")
            print(f"  solo  sel[0]: {solo['sel'][0]}")
            print(f"  shard sel[0]: {shard['sel'][0]}")
    return 2 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
