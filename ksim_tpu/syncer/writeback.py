"""Live-cluster write-back: the store reflector's apiserver side.

The reference's headline SDK promise is running the debuggable scheduler
against a REAL cluster: its scheduler binds live pods through a clientset
and the store reflector writes every recorded result back onto them as
annotations (reference simulator/docs/debuggable-scheduler.md:64,
pkg/debuggablescheduler/debuggable_scheduler.go:157-173,
scheduler/storereflector/storereflector.go:78-146).

ksim-tpu schedules a live cluster by composition: ``Syncer`` mirrors the
apiserver into the in-memory store, ``SchedulerService`` schedules the
mirror (in-store binds give the engine its sequential-commit semantics),
and this module closes the loop — it subscribes to the STORE's watch
stream (the same signal the reference's reflector takes from its pod
informer) and pushes each scheduling outcome to the apiserver:

- a pod that gained ``spec.nodeName`` is bound live via the binding
  subresource (POST .../binding — upstream DefaultBinder's verb; 409
  means someone else bound it first and is treated as settled);
- recorded result annotations (the ``kube-scheduler-simulator.sigs.k8s.io/``
  keys, including on UNSCHEDULABLE pods) are merge-patched onto the live
  pod with bounded conflict retry.

Termination is structural: the syncer's mandatory pod filter never
mirrors updates to already-scheduled live pods (syncer.py _filter_pod,
reference resource.go:103-123), so the authoritative MODIFIED our own
writes produce cannot re-enter the store and re-trigger a push; a
last-pushed cache additionally dedupes annotation-only churn.

Opt-in: writing to a user's cluster is a side effect the simulator must
never produce implicitly — gate on ``KSIM_ALLOW_LIVE_WRITEBACK=1`` (the
same pattern as exec credential plugins), or construct LiveWriteBack
explicitly in library use.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE
from ksim_tpu.state.cluster import ADDED, DELETED, MODIFIED, ClusterStore
from ksim_tpu.state.resources import JSON, name_of, namespace_of
from ksim_tpu.syncer.kubeapi import KubeApiError, KubeApiSource

logger = logging.getLogger(__name__)

RESULT_PREFIX = "kube-scheduler-simulator.sigs.k8s.io/"


def _pod_key(pod: JSON) -> str:
    return f"{namespace_of(pod) or 'default'}/{name_of(pod)}"


def _source_uid(pod: JSON) -> str:
    """The pod's LIVE cluster UID, recorded by the syncer at mirror time
    (syncer.SOURCE_UID_ANNOTATION — the mandatory mutators strip
    metadata.uid, so the store's own uid never matches the live one).
    Empty for store-local pods that never existed live."""
    from ksim_tpu.syncer.syncer import SOURCE_UID_ANNOTATION

    ann = pod.get("metadata", {}).get("annotations") or {}
    return ann.get(SOURCE_UID_ANNOTATION) or ""


def writeback_enabled() -> bool:
    return os.environ.get("KSIM_ALLOW_LIVE_WRITEBACK", "") == "1"


class LiveWriteBack:
    """Mirror scheduling outcomes from ``store`` onto the live cluster
    behind ``source``.  One daemon thread; errors are logged and never
    propagate into the scheduling loop (the reference's reflector
    likewise only logs, storereflector.go:139-142)."""

    #: transient-failure retry policy: a bind/patch that dies on a
    #: non-404/409 error (apiserver blip) re-runs up to this many times
    #: with linear backoff — without it the write would be lost forever,
    #: because the syncer never re-mirrors scheduled pods (no future
    #: store event retriggers the push) and the store would silently
    #: diverge from the live cluster.
    RETRY_ATTEMPTS = 5
    RETRY_DELAY_S = 2.0
    #: parking delay for a DELETED event that arrives before its
    #: eviction mark (note_eviction runs after the store delete returns,
    #: so the event can race ahead by a few µs).
    RECHECK_DELAY_S = 0.2

    def __init__(self, source: KubeApiSource, store: ClusterStore) -> None:
        self._source = source
        self._store = store
        self._stream = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # ns/name -> node already bound live; ns/name -> last annotation
        # set pushed (the sorted item tuple itself — equality comparison,
        # no hash fingerprint whose collision would silently skip a
        # push); ns/name set that 404ed (local-only pods — logged once,
        # then ignored).
        self._bound: dict[str, str] = {}
        self._pushed: dict[str, tuple] = {}
        self._missing: set[str] = set()
        # ns/name keys whose store delete is a PREEMPTION EVICTION
        # (note_eviction, fed by SchedulerService.add_eviction_listener).
        # Only these propagate as live deletes: a reset or a user delete
        # through the simulator API must never remove real workloads.
        # Keys stay until the live delete succeeds, so a transient
        # failure's retry still knows to evict.
        self._evictions: set[str] = set()
        # ns/name keys another scheduler bound to a DIFFERENT node than
        # the store says (the 409-reconcile outcome): later MODIFIED
        # events for them must not re-attempt the guaranteed-409 bind.
        self._diverged: set[str] = set()
        # (due_monotonic, etype, pod, attempt) pending transient retries.
        self._retries: list[tuple[float, str, JSON, int]] = []

    def note_eviction(self, namespace: str, name: str) -> None:
        """Mark the next store delete of this pod as a preemption
        eviction (wire via SchedulerService.add_eviction_listener)."""
        self._evictions.add(f"{namespace or 'default'}/{name}")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "LiveWriteBack":
        # list_first replays current pods as ADDED — _handle uses the
        # replay to SEED the bound/pushed caches (state that predates us
        # is treated as settled; only MODIFIED events write).
        self._stream = self._store.watch(("pods",), list_first=("pods",))
        self._thread = threading.Thread(
            target=self._run, name="live-writeback", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._stream is not None:
            self._stream.close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- event loop ----------------------------------------------------------

    def _run(self) -> None:  # ksimlint: thread-role(service-loop)
        try:
            while not self._stop.is_set():
                try:
                    event = self._stream.next(timeout=0.5)
                except Exception:
                    if not self._stop.is_set():
                        logger.exception("write-back watch failed; stopping")
                    return
                if event is not None:
                    self._dispatch(event.event_type, event.obj, attempt=0)
                # Due transient retries.
                if self._retries:
                    now = time.monotonic()
                    due = [r for r in self._retries if r[0] <= now]
                    self._retries = [r for r in self._retries if r[0] > now]
                    for _t, etype, pod, attempt in due:
                        self._dispatch(etype, pod, attempt=attempt)
        finally:
            # Exit (stop or watch failure) must not strand eviction
            # work — a marked eviction would otherwise never delete the
            # live victim (the overcommit this machinery exists to
            # prevent).  Two places can hold it: the stream queue
            # (events enqueued but not yet dispatched; close() only
            # stops NEW deliveries — already-enqueued events stay
            # readable, which is what makes this drain possible) and
            # the DELETED-recheck parking list.  Both drain with
            # final-attempt semantics (a failure logs PERMANENTLY
            # failed rather than re-queueing).  Events whose eviction
            # mark hasn't landed yet (a preemption mid-flight at stop
            # time: store delete done, note_eviction pending) get one
            # grace sleep before the final dispatch so the mark can
            # arrive.
            work: list[JSON] = []
            dropped: list[str] = []
            while True:
                try:
                    event = self._stream.next(timeout=0)
                except Exception:
                    break
                if event is None:
                    break
                if event.event_type == DELETED:
                    work.append(event.obj)
                elif event.event_type == MODIFIED:
                    dropped.append(f"queued {event.event_type} {_pod_key(event.obj)}")
            pending, self._retries = self._retries, []
            work.extend(pod for _t, et, pod, _a in pending if et == DELETED)
            dropped.extend(
                f"pending {et} retry (attempt {a}) {_pod_key(pod)}"
                for _t, et, pod, a in pending
                if et != DELETED
            )
            if dropped:
                # Only eviction (DELETED) work drains with final-attempt
                # semantics; everything else dies with the thread, and the
                # live cluster silently diverges from the store for those
                # pods — say which ones, so the operator can reconcile.
                logger.warning(
                    "write-back exiting with %d undelivered non-eviction "
                    "update(s) dropped (store/live divergence for these "
                    "pods): %s",
                    len(dropped),
                    "; ".join(dropped[:20])
                    + ("; ..." if len(dropped) > 20 else ""),
                )
            if any(_pod_key(p) not in self._evictions for p in work):
                # Bounded regardless of RECHECK_DELAY_S tuning: the
                # mark race is microseconds-scale, and stop()'s 5s
                # thread join must outlive this sleep plus the final
                # dispatches.
                time.sleep(min(self.RECHECK_DELAY_S + 0.05, 1.0))
            for pod in work:
                self._dispatch(DELETED, pod, attempt=self.RETRY_ATTEMPTS - 1)

    def _dispatch(self, etype: str, pod: JSON, *, attempt: int) -> None:
        if etype == DELETED and attempt == 0:
            key = _pod_key(pod)
            if key not in self._evictions:
                # Eviction marks are set right AFTER the store delete
                # returns, so a DELETED event can race a few µs ahead of
                # its mark.  One short recheck before treating it as a
                # plain (never-propagated) delete; a genuinely plain
                # delete just no-ops twice.
                self._retries.append(
                    (time.monotonic() + self.RECHECK_DELAY_S, DELETED, pod, 1)
                )
                return
        if attempt > 0 and etype != DELETED:
            # Retry with the pod's CURRENT store state, not the snapshot
            # captured at failure time — a newer pass may have pushed
            # fresher annotations in between, and replaying the stale
            # snapshot would overwrite them live and poison _pushed.
            from ksim_tpu.errors import SimulatorError

            try:
                pod = self._store.get(
                    "pods", name_of(pod), namespace_of(pod) or "default"
                )
            except SimulatorError:
                return  # gone from the store: nothing left to push
        try:
            self._handle(etype, pod)
        except Exception:
            if attempt + 1 < self.RETRY_ATTEMPTS and not self._stop.is_set():
                logger.warning(
                    "write-back failed for pod %s (attempt %d/%d); will retry",
                    name_of(pod), attempt + 1, self.RETRY_ATTEMPTS,
                    exc_info=True,
                )
                self._retries.append(
                    (
                        time.monotonic() + self.RETRY_DELAY_S * (attempt + 1),
                        etype,
                        pod,
                        attempt + 1,
                    )
                )
            else:
                logger.exception(
                    "write-back PERMANENTLY failed for pod %s — the live "
                    "cluster now diverges from the store for this pod",
                    name_of(pod),
                )

    def _handle(self, etype: str, pod: JSON) -> None:
        with TRACE.span("writeback.push", etype=etype):
            self._handle_traced(etype, pod)

    def _handle_traced(self, etype: str, pod: JSON) -> None:
        # Fault-plane site: an injected failure here exercises the
        # transient-retry policy above exactly like an apiserver blip.
        FAULTS.check("writeback.push")
        ns = namespace_of(pod) or "default"
        key = _pod_key(pod)
        if etype == DELETED:
            self._bound.pop(key, None)
            self._pushed.pop(key, None)
            self._missing.discard(key)
            self._diverged.discard(key)
            if key in self._evictions:
                # A preemption victim (note_eviction provenance) must be
                # evicted live too — without it the node would carry both
                # the victim and the preemptor (overcommit).  Any OTHER
                # store delete (reset, user delete through the simulator
                # API) never touches the real cluster.  The key leaves
                # the set only on success/404/409, so a transient
                # failure's retry still evicts.  The victim's UID from
                # the store event rides as a delete precondition
                # (kubeapi.delete_pod): a same-name pod RECREATED live
                # since this event answers 409 and survives — closing
                # the delete-the-wrong-pod window the reference guards
                # with the same precondition (storereflector.go:94-96).
                try:
                    self._source.delete_pod(ns, name_of(pod), uid=_source_uid(pod))
                    logger.info("evicted live pod %s (preemption)", key)
                except KubeApiError as e:
                    if e.code == 409:
                        logger.warning(
                            "live pod %s has a different UID than the "
                            "evicted victim (recreated since); leaving it",
                            key,
                        )
                    elif e.code != 404:
                        raise
                self._evictions.discard(key)
            return
        if etype not in (ADDED, MODIFIED) or key in self._missing:
            return
        node = pod.get("spec", {}).get("nodeName") or ""
        ann = {
            k: v
            for k, v in (pod.get("metadata", {}).get("annotations") or {}).items()
            if k.startswith(RESULT_PREFIX)
        }
        if etype == ADDED:
            # ADDED events are state that predates us: the startup
            # list_first replay, or the syncer mirroring a live pod that
            # is ALREADY bound/annotated.  Seed the caches instead of
            # writing — a restart against a 5000-pod cluster must not
            # fire 5000 guaranteed-409 binds and identity patches.  Our
            # own scheduling outcomes always arrive as MODIFIED (the
            # reference reflector likewise reacts to pod UPDATE events
            # only, storereflector.go:78-80).
            if node:
                self._bound[key] = node
            if ann:
                self._pushed[key] = tuple(sorted(ann.items()))
            return
        if not node and not ann:
            return
        try:
            if node and key in self._diverged:
                return  # settled on another scheduler's node; stop pushing
            if node and self._bound.get(key) != node:
                try:
                    self._source.bind_pod(ns, name_of(pod), node)
                except KubeApiError as e:
                    if e.code != 409:
                        raise
                    # Another scheduler bound it first (or a previous
                    # life of this process did).  Learn the REAL node —
                    # pushing result annotations that name OUR node onto
                    # a pod running elsewhere would be authoritative-
                    # looking misinformation.
                    live = self._source.get_pod(ns, name_of(pod))
                    live_uid = live.get("metadata", {}).get("uid") or ""
                    our_uid = _source_uid(pod)
                    if live_uid and our_uid and live_uid != our_uid:
                        # Same name, DIFFERENT pod: the live one was
                        # recreated since our store mirrored it.  Its
                        # node is meaningless for us, and writing our
                        # result annotations onto it would label a
                        # stranger — stop pushing for this key.
                        logger.warning(
                            "live pod %s has UID %s, store has %s "
                            "(recreated); skipping write-back",
                            key, live_uid, our_uid,
                        )
                        self._diverged.add(key)
                        return
                    real = live.get("spec", {}).get("nodeName") or ""
                    self._bound[key] = real
                    if real != node:
                        logger.warning(
                            "pod %s bound live to %s, not our %s; "
                            "skipping result annotations",
                            key, real or "<none>", node,
                        )
                        self._diverged.add(key)
                        return
                self._bound[key] = node
            if ann:
                fp = tuple(sorted(ann.items()))
                if self._pushed.get(key) != fp:
                    self._source.patch_pod_annotations(ns, name_of(pod), ann)
                    self._pushed[key] = fp
        except KubeApiError as e:
            if e.code == 404:
                # Local-only pod (created through the simulator API, not
                # present on the live cluster): nothing to write back.
                logger.info("pod %s not on the live cluster; skipping", key)
                self._missing.add(key)
            else:
                raise
