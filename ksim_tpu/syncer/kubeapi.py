"""Live kube-apiserver source for import/sync — plain REST, no client-go.

The reference's one-shot importer and resource syncer run against a REAL
cluster through client-go dynamic informers (reference
simulator/syncer/syncer.go:45-91, cmd/simulator/simulator.go:59-71,
config kubeConfig field config/config.go:88-114).  This module is the
TPU-build equivalent: a ``SourceCluster`` over the kube-apiserver's HTTP
API built on the stdlib —

- ``load_kubeconfig`` parses a kubeconfig file (cluster server URL, CA /
  client-cert TLS material inline or by path, bearer token, basic auth,
  insecure-skip-tls-verify) without any kubernetes client dependency;
- ``KubeApiSource.list`` GETs ``/api/v1/<resource>`` (or the storage/
  scheduling API groups) cluster-wide;
- ``KubeApiSource.watch`` runs one reader thread per kind over
  ``?watch=1&resourceVersion=<rv>&allowWatchBookmarks=true`` streams with
  the client-go RetryWatcher semantics (reference
  resourcewatcher/resourcewatcher.go:128-134): reconnect-with-resume on
  connection drops, bookmark handling, and — one step beyond RetryWatcher,
  matching what a shared informer's relist gives the reference syncer — a
  410 Gone triggers a LIST diffed against the known key set, emitting
  synthetic ADDED/MODIFIED/DELETED events so the mirror converges even
  across an etcd compaction;
- ``KubeApiSource.snap`` shapes a LIST of all 7 kinds like
  ``SnapshotService.snap`` so ``OneShotImporter`` can replicate a live
  cluster (reference oneshotimporter/importer.go:44-59 snaps through the
  same service interface).

Events are ``state.cluster.WatchEvent``s, so ``Syncer`` consumes this
source exactly like an in-memory ``ClusterStore``.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import queue
import ssl
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

from ksim_tpu.errors import InvalidConfigError, SimulatorError
from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE
from ksim_tpu.state.cluster import ADDED, DELETED, KINDS, MODIFIED, WatchEvent
from ksim_tpu.state.resources import JSON, labels_of, name_of, namespace_of
from ksim_tpu.state.selectors import match_label_selector

logger = logging.getLogger(__name__)


class KubeApiError(SimulatorError):
    """A kube-apiserver request failure; ``code`` is the HTTP status
    (0 for transport errors) so callers can branch on 404/409."""

    def __init__(self, message: str, *, code: int = 0) -> None:
        super().__init__(message)
        self.code = code


# kind -> (API path prefix, List kind name).  All lists are cluster-wide
# (the reference's dynamic informer factory watches every namespace).
_API_PATHS: dict[str, str] = {
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "namespaces": "/api/v1/namespaces",
    "persistentvolumes": "/api/v1/persistentvolumes",
    "persistentvolumeclaims": "/api/v1/persistentvolumeclaims",
    "storageclasses": "/apis/storage.k8s.io/v1/storageclasses",
    "priorityclasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
}

# Snapshot-JSON field names per kind (state/snapshot.py _FIELD_KINDS).
_SNAP_FIELDS = (
    ("pods", "pods"),
    ("nodes", "nodes"),
    ("pvs", "persistentvolumes"),
    ("pvcs", "persistentvolumeclaims"),
    ("storageClasses", "storageclasses"),
    ("priorityClasses", "priorityclasses"),
    ("namespaces", "namespaces"),
)

# Server-side watch window; the server closes the stream cleanly after
# this many seconds and the reader reconnects with its resume version.
WATCH_TIMEOUT_S = 300
RECONNECT_BACKOFF_S = 1.0


def _b64_to_tempfile(data_b64: str, suffix: str) -> str:
    raw = base64.b64decode(data_b64)
    f = tempfile.NamedTemporaryFile(prefix="ksim_kubecfg_", suffix=suffix, delete=False)
    with f:
        f.write(raw)
    return f.name


def load_kubeconfig(path: str, context: str | None = None) -> dict[str, Any]:
    """Parse a kubeconfig into connection settings.

    Returns {server, headers, ssl_context}; raises
    InvalidConfigError on a missing/odd file.  Supported auth: bearer
    ``token`` / ``tokenFile``, basic ``username``/``password``, client
    certificates (path or inline ``-data``), and — behind the explicit
    ``KSIM_ALLOW_EXEC_CREDENTIALS=1`` opt-in — ``exec`` credential
    plugins (the client-go ExecCredential protocol GKE/EKS kubeconfigs
    use; running an operator-supplied command is a code-execution
    capability, hence the gate, like builderImport's)."""
    import yaml

    try:
        with open(os.path.expanduser(path)) as f:
            cfg = yaml.safe_load(f) or {}
    except OSError as e:
        raise InvalidConfigError(f"kubeconfig {path!r}: {e}") from None

    ctx_name = context or cfg.get("current-context")
    contexts = {c.get("name"): c.get("context") or {} for c in cfg.get("contexts") or []}
    if not ctx_name or ctx_name not in contexts:
        raise InvalidConfigError(f"kubeconfig {path!r}: no usable context {ctx_name!r}")
    ctx = contexts[ctx_name]
    clusters = {c.get("name"): c.get("cluster") or {} for c in cfg.get("clusters") or []}
    users = {u.get("name"): u.get("user") or {} for u in cfg.get("users") or []}
    cluster = clusters.get(ctx.get("cluster"))
    if cluster is None or not cluster.get("server"):
        raise InvalidConfigError(f"kubeconfig {path!r}: context {ctx_name!r} has no cluster server")
    user = users.get(ctx.get("user"), {})
    headers_expiry: float | None = None
    headers_refresh = None
    if user.get("exec"):
        if os.environ.get("KSIM_ALLOW_EXEC_CREDENTIALS") != "1":
            raise InvalidConfigError(
                f"kubeconfig {path!r}: exec credential plugins run an "
                "operator-supplied command; enable with "
                "KSIM_ALLOW_EXEC_CREDENTIALS=1"
            )
        creds = _exec_credentials(path, user["exec"])
        headers_expiry = creds.pop("_expiry", None)
        user = dict(user, **creds)
        if creds.get("token"):
            # Exec tokens expire (EKS ~15 min): the source re-runs the
            # plugin near expiry / on 401.  Cert-data exec creds refresh
            # only at construction (rebuilding the TLS context mid-flight
            # is not supported).
            exec_spec = user["exec"]

            def headers_refresh() -> "tuple[dict[str, str], float | None]":
                fresh = _exec_credentials(path, exec_spec)
                return (
                    {"Authorization": f"Bearer {fresh['token']}"}
                    if fresh.get("token")
                    else {},
                    fresh.pop("_expiry", None),
                )

    server: str = cluster["server"].rstrip("/")
    headers: dict[str, str] = {}

    token = user.get("token")
    if not token and user.get("tokenFile"):
        try:
            with open(os.path.expanduser(user["tokenFile"])) as f:
                token = f.read().strip()
        except OSError as e:
            raise InvalidConfigError(f"kubeconfig {path!r}: tokenFile: {e}") from None
    if token:
        headers["Authorization"] = f"Bearer {token}"
    elif user.get("username") is not None:
        basic = f"{user.get('username', '')}:{user.get('password', '')}"
        headers["Authorization"] = "Basic " + base64.b64encode(basic.encode()).decode()

    ssl_context: ssl.SSLContext | None = None
    if server.startswith("https"):
        try:
            ssl_context = _build_ssl_context(path, cluster, user)
        except (OSError, ssl.SSLError) as e:
            # Missing/garbled CA or client-cert files surface as config
            # errors, per this function's contract.
            raise InvalidConfigError(f"kubeconfig {path!r}: TLS material: {e}") from None

    return {
        "server": server,
        "headers": headers,
        "ssl_context": ssl_context,
        "headers_expiry": headers_expiry,
        "headers_refresh": headers_refresh,
    }


EXEC_CREDENTIAL_TIMEOUT_S = 20.0


def _exec_credentials(path: str, spec: dict) -> dict:
    """Run a client-go exec credential plugin (client-go
    tools/clientcmd/api ExecConfig -> ExecCredential.status) and map its
    status onto kubeconfig user fields: ``token``,
    ``clientCertificateData``/``clientKeyData`` -> ``client-*-data``
    (base64'd, our cert loader's inline form).  Watchdogged subprocess;
    any failure is an InvalidConfigError — auth must fail loudly."""
    import subprocess

    command = spec.get("command")
    if not command:
        raise InvalidConfigError(f"kubeconfig {path!r}: exec plugin has no command")
    env = dict(os.environ)
    for pair in spec.get("env") or []:
        if pair.get("name"):
            env[pair["name"]] = pair.get("value", "")
    # The protocol hands the plugin its own apiVersion + non-interactive
    # mode via KUBERNETES_EXEC_INFO.
    env["KUBERNETES_EXEC_INFO"] = json.dumps(
        {
            "apiVersion": spec.get("apiVersion")
            or "client.authentication.k8s.io/v1",
            "kind": "ExecCredential",
            "spec": {"interactive": False},
        }
    )
    try:
        proc = subprocess.run(
            [command, *(spec.get("args") or [])],
            env=env,
            capture_output=True,
            text=True,
            timeout=EXEC_CREDENTIAL_TIMEOUT_S,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise InvalidConfigError(
            f"kubeconfig {path!r}: exec plugin {command!r}: {e}"
        ) from None
    if proc.returncode != 0:
        raise InvalidConfigError(
            f"kubeconfig {path!r}: exec plugin {command!r} exited "
            f"{proc.returncode}: {proc.stderr.strip()[:200]}"
        )
    try:
        status = (json.loads(proc.stdout) or {}).get("status") or {}
    except json.JSONDecodeError as e:
        raise InvalidConfigError(
            f"kubeconfig {path!r}: exec plugin {command!r} output: {e}"
        ) from None
    out: dict = {}
    if status.get("expirationTimestamp"):
        # RFC3339 -> epoch; an unparseable stamp means "no expiry known".
        import datetime

        try:
            out["_expiry"] = datetime.datetime.fromisoformat(
                status["expirationTimestamp"].replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            pass
    if status.get("token"):
        out["token"] = status["token"]
    if status.get("clientCertificateData"):
        out["client-certificate-data"] = base64.b64encode(
            status["clientCertificateData"].encode()
        ).decode()
    if status.get("clientKeyData"):
        out["client-key-data"] = base64.b64encode(
            status["clientKeyData"].encode()
        ).decode()
    if not any(k in out for k in ("token", "client-certificate-data", "client-key-data")):
        raise InvalidConfigError(
            f"kubeconfig {path!r}: exec plugin {command!r} returned no credentials"
        )
    return out


def _build_ssl_context(path: str, cluster: dict, user: dict) -> ssl.SSLContext:
    ssl_context = ssl.create_default_context()
    if cluster.get("insecure-skip-tls-verify"):
        ssl_context.check_hostname = False
        ssl_context.verify_mode = ssl.CERT_NONE
    elif cluster.get("certificate-authority-data"):
        ssl_context.load_verify_locations(
            cadata=base64.b64decode(cluster["certificate-authority-data"]).decode()
        )
    elif cluster.get("certificate-authority"):
        ssl_context.load_verify_locations(
            cafile=os.path.expanduser(cluster["certificate-authority"])
        )
    cert = user.get("client-certificate")
    key = user.get("client-key")
    # Inline -data material goes through short-lived temp files only
    # because load_cert_chain requires paths; it reads them eagerly, so
    # they are unlinked before returning — the decoded private key never
    # outlives this call on disk.
    temp_files: list[str] = []
    try:
        if user.get("client-certificate-data"):
            cert = _b64_to_tempfile(user["client-certificate-data"], ".crt")
            temp_files.append(cert)
        if user.get("client-key-data"):
            key = _b64_to_tempfile(user["client-key-data"], ".key")
            temp_files.append(key)
        if cert and key:
            ssl_context.load_cert_chain(
                os.path.expanduser(cert), os.path.expanduser(key)
            )
    finally:
        for p in temp_files:
            try:
                os.unlink(p)
            except OSError:
                pass
    return ssl_context


class KubeApiSource:
    """``syncer.SourceCluster`` + ``OneShotImporter`` export side over a
    live kube-apiserver."""

    def __init__(
        self,
        server: str,
        *,
        headers: dict[str, str] | None = None,
        ssl_context: ssl.SSLContext | None = None,
        request_timeout: float = 30.0,
        headers_expiry: float | None = None,
        headers_refresh=None,
    ) -> None:
        self._server = server.rstrip("/")
        self._headers = dict(headers or {})
        self._ssl = ssl_context
        self._timeout = request_timeout
        # Exec-credential rotation (load_kubeconfig): refresh() returns
        # (new auth headers, new expiry epoch).  Checked before every
        # request and retried once on 401 — long-running syncers outlive
        # EKS/GKE token TTLs.
        self._headers_expiry = headers_expiry
        self._headers_refresh = headers_refresh
        self._refresh_lock = threading.Lock()

    @classmethod
    def from_kubeconfig(cls, path: str, context: str | None = None) -> "KubeApiSource":
        return cls(**load_kubeconfig(path, context))

    def close(self) -> None:
        """No per-source resources to release (kept for callers that
        treat sources as closable handles)."""

    # -- HTTP ----------------------------------------------------------------

    def _maybe_refresh_auth(self, *, force: bool = False) -> None:
        if self._headers_refresh is None:
            return
        with self._refresh_lock:
            stale = force or (
                self._headers_expiry is not None
                and time.time() > self._headers_expiry - 60
            )
            if not stale:
                return
            try:
                fresh, expiry = self._headers_refresh()
            except Exception as e:
                raise SimulatorError(f"credential refresh failed: {e}") from None
            self._headers.update(fresh)
            self._headers_expiry = expiry

    def _open(self, path: str, query: dict[str, Any], timeout: float):
        # Same fault-plane site as _request: "kubeapi.request" covers
        # EVERY apiserver HTTP call, list/watch GETs included, so a
        # chaos run exercises the relist/410-resume recovery paths too.
        # The span covers connection setup only — for a watch stream the
        # body is consumed long after this returns.
        with TRACE.span("kubeapi.request", method="GET", path=path, stream=True):
            FAULTS.check("kubeapi.request")
            url = self._server + path
            if query:
                url += "?" + urllib.parse.urlencode(query)
            self._maybe_refresh_auth()
            for attempt in (0, 1):
                req = urllib.request.Request(url, headers=self._headers)
                try:
                    return urllib.request.urlopen(
                        req, timeout=timeout, context=self._ssl
                    )
                except urllib.error.HTTPError as e:
                    if (
                        e.code == 401
                        and attempt == 0
                        and self._headers_refresh is not None
                    ):
                        # Token died before its advertised expiry: one
                        # forced re-exec, then the retry below.
                        self._maybe_refresh_auth(force=True)
                        continue
                    body = e.read(4096).decode(errors="replace")
                    raise SimulatorError(
                        f"GET {path}: HTTP {e.code}: {body[:200]}"
                    ) from None
                except (urllib.error.URLError, OSError, ssl.SSLError) as e:
                    raise SimulatorError(f"GET {path}: {e}") from None

    def _request(
        self,
        method: str,
        path: str,
        body: JSON | None = None,
        *,
        content_type: str = "application/json",
        timeout: float | None = None,
    ) -> JSON:
        """One non-streaming request with the same auth-refresh/401-retry
        protocol as ``_open``.  Raises KubeApiError carrying the HTTP
        status so callers can branch on 404/409."""
        with TRACE.span("kubeapi.request", method=method, path=path):
            # Fault-plane site: injected before the wire so chaos runs
            # can fail/hang any apiserver request without a cooperating
            # server.
            FAULTS.check("kubeapi.request")
            url = self._server + path
            data = None if body is None else json.dumps(body).encode()
            self._maybe_refresh_auth()
            for attempt in (0, 1):
                headers = dict(self._headers)
                if data is not None:
                    headers["Content-Type"] = content_type
                req = urllib.request.Request(
                    url, data=data, headers=headers, method=method
                )
                try:
                    with urllib.request.urlopen(
                        req, timeout=timeout or self._timeout, context=self._ssl
                    ) as resp:
                        raw = resp.read()
                        return json.loads(raw) if raw else {}
                except urllib.error.HTTPError as e:
                    if (
                        e.code == 401
                        and attempt == 0
                        and self._headers_refresh is not None
                    ):
                        self._maybe_refresh_auth(force=True)
                        continue
                    detail = e.read(4096).decode(errors="replace")
                    raise KubeApiError(
                        f"{method} {path}: HTTP {e.code}: {detail[:200]}",
                        code=e.code,
                    ) from None
                except (urllib.error.URLError, OSError, ssl.SSLError) as e:
                    raise KubeApiError(f"{method} {path}: {e}") from None

    # -- write verbs (live scheduling write-back) ----------------------------
    #
    # The reference's debuggable scheduler binds REAL pods through its
    # clientset and its store reflector writes the result annotations back
    # onto them with get-latest + update + conflict retry
    # (reference simulator/pkg/debuggablescheduler/debuggable_scheduler.go:
    # 157-173, scheduler/storereflector/storereflector.go:78-146).

    def get_pod(self, namespace: str, name: str) -> JSON:
        """The live pod object — used to reconcile a 409 on bind (learn
        which node another scheduler actually chose)."""
        ns = namespace or "default"
        return self._request("GET", f"/api/v1/namespaces/{ns}/pods/{name}")

    def delete_pod(self, namespace: str, name: str, *, uid: str = "") -> None:
        """DELETE a live pod — the write-back's eviction verb for
        preemption victims (upstream preemption evicts via the pod
        DELETE/eviction API).

        ``uid`` ships as DeleteOptions.preconditions.uid (the reference's
        reflector guards its deletes the same way, storereflector.go:94-96):
        a same-name pod RECREATED since the store event then answers 409
        instead of being deleted — without it, the window between the
        store delete and this call could kill an innocent new pod."""
        ns = namespace or "default"
        body: JSON | None = None
        if uid:
            body = {
                "apiVersion": "v1",
                "kind": "DeleteOptions",
                "preconditions": {"uid": uid},
            }
        self._request("DELETE", f"/api/v1/namespaces/{ns}/pods/{name}", body)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        """POST the binding subresource — exactly what upstream's
        DefaultBinder does.  An already-bound pod answers 409; callers
        treat that as someone-else-bound."""
        ns = namespace or "default"
        self._request(
            "POST",
            f"/api/v1/namespaces/{ns}/pods/{name}/binding",
            {
                "apiVersion": "v1",
                "kind": "Binding",
                "metadata": {"name": name, "namespace": ns},
                "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
            },
        )

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: dict[str, str], *, attempts: int = 4
    ) -> None:
        """Merge-patch result annotations onto a live pod.  RFC 7386
        merges ``metadata.annotations`` key-wise, so only our keys are
        written — the reference's get+update achieves the same effect
        with an explicit conflict retry (storereflector.go:116-136);
        merge patches rarely conflict, but a concurrent full-object
        writer can still 409, hence the bounded retry."""
        ns = namespace or "default"
        body = {"metadata": {"annotations": dict(annotations)}}
        for attempt in range(attempts):
            try:
                self._request(
                    "PATCH",
                    f"/api/v1/namespaces/{ns}/pods/{name}",
                    body,
                    content_type="application/merge-patch+json",
                )
                return
            except KubeApiError as e:
                if e.code != 409 or attempt == attempts - 1:
                    raise
                time.sleep(min(0.1 * 2**attempt, 1.0))

    # -- SourceCluster -------------------------------------------------------

    def list_with_rv(self, kind: str, namespace: str = "") -> tuple[list[JSON], str]:
        """LIST one kind cluster-wide; returns (items, listResourceVersion)
        — the rv is the watch-resume point."""
        path = _API_PATHS.get(kind)
        if path is None:
            raise SimulatorError(f"unknown kind {kind!r}")
        with self._open(path, {}, self._timeout) as resp:
            body = json.load(resp)
        items = body.get("items") or []
        if namespace:
            items = [o for o in items if namespace_of(o) == namespace]
        rv = str((body.get("metadata") or {}).get("resourceVersion") or "")
        return items, rv

    def list(self, kind: str, namespace: str = "") -> list[JSON]:
        return self.list_with_rv(kind, namespace)[0]

    def watch(self, kinds: tuple[str, ...] = KINDS) -> "KubeWatchStream":
        return KubeWatchStream(self, kinds)

    # -- OneShotImporter export side ----------------------------------------

    def snap(self, label_selector: JSON | None = None) -> JSON:
        """Shape a live LIST like SnapshotService.snap (the reference snaps
        the external cluster through the same snapshot service,
        oneshotimporter/importer.go:44-59).  Scheduler config is never
        read from a live cluster."""
        from ksim_tpu.state.snapshot import is_ignored_namespace, is_system_priority_class

        out: JSON = {}
        for field, kind in _SNAP_FIELDS:
            objs = self.list(kind)
            if label_selector:
                objs = [o for o in objs if match_label_selector(label_selector, labels_of(o))]
            if field == "priorityClasses":
                objs = [o for o in objs if not is_system_priority_class(name_of(o))]
            if field == "namespaces":
                objs = [o for o in objs if not is_ignored_namespace(name_of(o))]
            out[field] = objs
        out["schedulerConfig"] = None
        return out


class KubeWatchStream:
    """Reconnecting multi-kind watch: one reader thread per kind feeding a
    shared queue; duck-types ``state.cluster.WatchStream``."""

    def __init__(self, source: KubeApiSource, kinds: tuple[str, ...]) -> None:
        self._source = source
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._stop = threading.Event()
        self._responses: dict[str, Any] = {}
        self._threads = []
        # Establish every kind's resume point SYNCHRONOUSLY, so by the
        # time this constructor returns the subscription covers all
        # changes after "now" — Syncer.run relies on subscribe-then-list
        # having no gap (a reader-thread first LIST could start later
        # than the syncer's own initial import and lose the in-between
        # events).  Raises on an unreachable apiserver: a sync source
        # that cannot even LIST should fail loudly at startup.
        resume: dict[str, tuple[str, set[str]]] = {}
        for kind in kinds:
            if kind not in _API_PATHS:
                raise SimulatorError(f"unknown kind {kind!r}")
            resume[kind] = self._relist(kind, set(), emit=False)
        for kind in kinds:
            rv, known = resume[kind]
            t = threading.Thread(
                target=self._run_kind, args=(kind, rv, known), daemon=True
            )
            self._threads.append(t)
            t.start()

    # -- consumer side -------------------------------------------------------

    def next(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()
        # Close-and-join in a sweep loop: a reader that was mid-reconnect
        # registers its response AFTER the first sweep, so keep closing
        # whatever appears while the joins drain.  A reader blocked inside
        # urlopen() itself cannot be interrupted (daemon thread; it
        # notices _stop as soon as the connect returns and closes its own
        # response before exiting — see _run_kind).
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            for resp in list(self._responses.values()):
                try:
                    resp.close()  # unblocks a reader parked in readline()
                except Exception:
                    pass
            alive = [t for t in self._threads if t.is_alive()]
            if not alive:
                break
            for t in alive:
                t.join(timeout=0.2)

    # -- reader side ---------------------------------------------------------

    def _relist(self, kind: str, known: set[str], emit: bool) -> tuple[str, set[str]]:
        """LIST to establish the watch-resume version.

        With ``emit`` (the 410-expiry path) this is the informer-relist
        analogue: replays objects already seen as MODIFIED and genuinely
        new ones as ADDED — an informer relist surfaces known objects as
        Update notifications, which is what keeps the syncer's mandatory
        scheduled-pod filter effective (reference resource.go:103-123; an
        ADDED replay would bypass it and clobber simulator-bound pods) —
        and synthesizes DELETED for keys that vanished during the gap.
        The stream-startup call does NOT emit — Syncer.sync_once does the
        initial import itself, and subscribing happens first, so events
        after this list's rv flow through the watch with no gap (matching
        ClusterStore.watch, which replays nothing unless asked)."""
        items, rv = self._source.list_with_rv(kind)
        fresh: set[str] = set()
        for obj in items:
            key = f"{namespace_of(obj)}/{name_of(obj)}"
            fresh.add(key)
            if emit:
                etype = MODIFIED if key in known else ADDED
                self._q.put(WatchEvent(kind, etype, obj))
        if emit:
            for gone in known - fresh:
                ns, _, name = gone.partition("/")
                self._q.put(
                    WatchEvent(
                        kind,
                        DELETED,
                        {"metadata": {"name": name, "namespace": ns}},
                    )
                )
        return rv, fresh

    def _run_kind(self, kind: str, rv: str | None, known: set[str]) -> None:  # ksimlint: thread-role(service-loop)
        path = _API_PATHS[kind]
        while not self._stop.is_set():
            try:
                if rv is None:
                    rv, known = self._relist(kind, known, emit=True)
                query = {
                    "watch": "1",
                    "allowWatchBookmarks": "true",
                    "timeoutSeconds": str(WATCH_TIMEOUT_S),
                }
                if rv:
                    query["resourceVersion"] = rv
                resp = self._source._open(path, query, WATCH_TIMEOUT_S + 30)
                self._responses[kind] = resp
                try:
                    # close() may have swept before we registered; don't
                    # park on a stream nobody will close again.
                    if self._stop.is_set():
                        return
                    for line in resp:
                        if self._stop.is_set():
                            return
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            logger.warning("%s watch: bad JSON line", kind)
                            continue
                        etype = ev.get("type")
                        obj = ev.get("object") or {}
                        if etype == "BOOKMARK":
                            rv = str((obj.get("metadata") or {}).get("resourceVersion") or rv)
                            continue
                        if etype == "ERROR":
                            if (obj.get("code") == 410) or ("too old" in str(obj.get("message", ""))):
                                logger.info("%s watch expired (410): relisting", kind)
                                rv = None
                            else:
                                # Back off before reconnecting: a
                                # persistent non-410 error would otherwise
                                # hot-loop against the apiserver (clean
                                # end-of-stream reconnects immediately).
                                logger.warning("%s watch error event: %s", kind, obj)
                                time.sleep(RECONNECT_BACKOFF_S)
                            break
                        if etype not in (ADDED, MODIFIED, DELETED):
                            continue
                        new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                        if new_rv:
                            rv = str(new_rv)
                        key = f"{namespace_of(obj)}/{name_of(obj)}"
                        if etype == DELETED:
                            known.discard(key)
                        else:
                            known.add(key)
                        self._q.put(WatchEvent(kind, etype, obj))
                finally:
                    self._responses.pop(kind, None)
                    try:
                        resp.close()
                    except Exception:
                        pass
            except SimulatorError as e:
                if self._stop.is_set():
                    return
                logger.warning("%s watch: %s; reconnecting", kind, e)
                time.sleep(RECONNECT_BACKOFF_S)
            except Exception:
                if self._stop.is_set():
                    return
                logger.exception("%s watch: reader failed; reconnecting", kind)
                time.sleep(RECONNECT_BACKOFF_S)
