"""Resource syncer: continuous import from a source cluster.

The reference mirrors a real cluster into the simulator through dynamic
shared informers with mandatory mutators and filters (reference
simulator/syncer/syncer.go:45-208, syncer/resource.go:18-123).  Here the
source is anything store-shaped (list + watch with the ClusterStore event
protocol) — typically another ClusterStore, or an adapter over a real
apiserver."""

from ksim_tpu.syncer.syncer import (
    ADD,
    DEFAULT_KINDS,
    UPDATE,
    Syncer,
    SyncerOptions,
)

__all__ = ["ADD", "DEFAULT_KINDS", "UPDATE", "Syncer", "SyncerOptions"]
