"""Continuous cluster-resource mirroring (reference simulator/syncer/).

Semantics preserved from the reference:

- **Sync order matters on first import**: namespaces -> priorityclasses ->
  storageclasses -> pvcs -> nodes -> pvs -> pods (reference
  resource.go:18-26 DefaultGVRs, "this order matters").
- **Mandatory mutators** (users cannot opt out, resource.go:37-41):
  every resource loses uid/resourceVersion/generation before import
  (syncer.go:174-181 removeUnnecessaryMetadata); pods additionally lose
  serviceAccountName and ownerReferences (resource.go:83-99 mutatePods);
  a Bound PV's claimRef UID is re-resolved against the DESTINATION's PVC
  (resource.go:56-81 mutatePV).
- **Mandatory filters** (resource.go:44-47): pod UPDATE events for
  already-scheduled pods are never mirrored (resource.go:103-123
  filterPods) — the simulator's scheduler owns binding.
- **User extension**: additional mutating/filtering functions per kind
  (syncer.go Options), called after the mandatory set.
- NotFound on update/delete is tolerated (syncer.go:244-269 — the
  scheduler may have preempted the pod, or a user deleted it).
"""

from __future__ import annotations

import copy
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Protocol

from ksim_tpu.errors import NotFoundError, SimulatorError
from ksim_tpu.state.cluster import ADDED, DELETED, MODIFIED, ClusterStore
from ksim_tpu.state.resources import JSON, name_of, namespace_of

logger = logging.getLogger(__name__)

# Sync order (reference resource.go:18-26).
DEFAULT_KINDS = (
    "namespaces",
    "priorityclasses",
    "storageclasses",
    "persistentvolumeclaims",
    "nodes",
    "persistentvolumes",
    "pods",
)

# Event kinds passed to mutators/filters (reference resource.go Event).
ADD = "add"
UPDATE = "update"

# fn(resource, dest_store, event) -> resource | None
MutatingFunction = Callable[[JSON, ClusterStore, str], JSON]
# fn(resource, dest_store, event) -> bool (False = skip)
FilteringFunction = Callable[[JSON, ClusterStore, str], bool]


class SourceCluster(Protocol):
    """What the syncer needs from a source: ClusterStore's list/watch."""

    def list(self, kind: str, namespace: str = "") -> list[JSON]: ...

    def watch(self, kinds: tuple[str, ...] = ...) -> object: ...


@dataclass
class SyncerOptions:
    kinds: tuple[str, ...] | None = None
    additional_mutating: dict[str, MutatingFunction] = field(default_factory=dict)
    additional_filtering: dict[str, FilteringFunction] = field(default_factory=dict)


# Mirrored pods remember their LIVE cluster UID here (the mandatory
# mutators strip metadata.uid, and the store then assigns its own): the
# write-back's eviction DELETE sends it as a precondition so a same-name
# pod recreated live since the mirror is never the one deleted
# (kubeapi.delete_pod; reference storereflector.go:94-96 — the
# reference's store keeps the live UID, ours records it out-of-band).
# Deliberately NOT under the result-annotation prefix: result keys are
# what the write-back pushes onto live pods.
SOURCE_UID_ANNOTATION = "ksim-tpu/source-uid"


def _strip_metadata(obj: JSON) -> JSON:
    """removeUnnecessaryMetadata (syncer.go:174-181)."""
    obj = dict(obj)
    md = dict(obj.get("metadata") or {})
    for k in ("uid", "resourceVersion", "generation", "managedFields"):
        md.pop(k, None)
    obj["metadata"] = md
    return obj


def _mutate_pod(obj: JSON, dest: ClusterStore, event: str) -> JSON:
    obj = dict(obj)
    spec = dict(obj.get("spec") or {})
    spec.pop("serviceAccountName", None)
    spec.pop("serviceAccount", None)
    obj["spec"] = spec
    md = dict(obj.get("metadata") or {})
    md.pop("ownerReferences", None)
    obj["metadata"] = md
    return obj


def _mutate_pv(obj: JSON, dest: ClusterStore, event: str) -> JSON:
    if (obj.get("status") or {}).get("phase") != "Bound":
        return obj
    ref = (obj.get("spec") or {}).get("claimRef")
    if not ref or not ref.get("name"):
        return obj
    try:
        pvc = dest.get(
            "persistentvolumeclaims", ref["name"], ref.get("namespace", "default")
        )
        uid = pvc["metadata"].get("uid")
    except SimulatorError:
        uid = None
    obj = dict(obj)
    spec = dict(obj.get("spec") or {})
    spec["claimRef"] = {**ref, "uid": uid}
    obj["spec"] = spec
    return obj


def _filter_pod(obj: JSON, dest: ClusterStore, event: str) -> bool:
    if event == ADD:
        return True
    # Never mirror updates to already-scheduled pods (resource.go:103-123).
    return not obj.get("spec", {}).get("nodeName")


_MANDATORY_MUTATING: dict[str, MutatingFunction] = {
    "pods": _mutate_pod,
    "persistentvolumes": _mutate_pv,
}
_MANDATORY_FILTERING: dict[str, FilteringFunction] = {
    "pods": _filter_pod,
}


class Syncer:
    """Mirror a source cluster's resources into the destination store."""

    def __init__(
        self,
        source: SourceCluster,
        dest: ClusterStore,
        options: SyncerOptions | None = None,
    ) -> None:
        options = options or SyncerOptions()
        self._source = source
        self._dest = dest
        self._kinds = tuple(options.kinds or DEFAULT_KINDS)
        self._mutating: dict[str, list[MutatingFunction]] = {}
        self._filtering: dict[str, list[FilteringFunction]] = {}
        for kind, fn in _MANDATORY_MUTATING.items():
            self._mutating.setdefault(kind, []).append(fn)
        for kind, fn in options.additional_mutating.items():
            self._mutating.setdefault(kind, []).append(fn)
        for kind, fn in _MANDATORY_FILTERING.items():
            self._filtering.setdefault(kind, []).append(fn)
        for kind, fn in options.additional_filtering.items():
            self._filtering.setdefault(kind, []).append(fn)
        # Kinds with USER extension functions get a private deep copy per
        # event in _prepare (see there).
        self._user_touched: dict[str, bool] = {}
        for kind in (*options.additional_mutating, *options.additional_filtering):
            self._user_touched[kind] = True
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one object ---------------------------------------------------------

    def _prepare(self, kind: str, obj: JSON, event: str) -> JSON | None:
        # Watch events share the SOURCE store's frozen dicts
        # (cluster.py _notify); USER filtering/mutating functions are
        # allowed to mutate what they receive, so give them a private
        # deep copy — corrupting the source store would also poison its
        # per-object featurization memos (state/objcache.py).  The
        # mandatory built-in fns are copy-on-write, so no copy is needed
        # when no user extension is registered for this kind.
        if self._user_touched.get(kind):
            obj = copy.deepcopy(obj)
        for fn in self._filtering.get(kind, ()):
            if not fn(obj, self._dest, event):
                return None
        src_uid = obj.get("metadata", {}).get("uid") if kind == "pods" else None
        obj = _strip_metadata(obj)
        for fn in self._mutating.get(kind, ()):
            obj = fn(obj, self._dest, event)
        if src_uid:
            md = obj["metadata"] = dict(obj.get("metadata") or {})
            md["annotations"] = dict(
                md.get("annotations") or {}, **{SOURCE_UID_ANNOTATION: src_uid}
            )
        return obj

    def _create(self, kind: str, obj: JSON) -> None:
        prepared = self._prepare(kind, obj, ADD)
        if prepared is None:
            return
        try:
            self._dest.apply(kind, prepared)
        except SimulatorError:
            logger.exception("failed to sync create %s/%s", kind, name_of(obj))

    def _update(self, kind: str, obj: JSON) -> None:
        prepared = self._prepare(kind, obj, UPDATE)
        if prepared is None:
            return
        try:
            self._dest.update(kind, prepared)
        except NotFoundError:
            # Tolerated: the scheduler may have preempted it, or a user
            # deleted it for debugging (syncer.go:244-250).
            logger.info("skip update of missing %s/%s", kind, name_of(obj))
        except SimulatorError:
            logger.exception("failed to sync update %s/%s", kind, name_of(obj))

    def _delete(self, kind: str, obj: JSON) -> None:
        try:
            self._dest.delete(kind, name_of(obj), namespace_of(obj))
        except NotFoundError:
            logger.info("skip delete of missing %s/%s", kind, name_of(obj))
        except SimulatorError:
            logger.exception("failed to sync delete %s/%s", kind, name_of(obj))

    # -- run ----------------------------------------------------------------

    def sync_once(self) -> None:
        """Initial LIST import in dependency order (the informer cache
        sync the reference does per-GVR before watching)."""
        for kind in self._kinds:
            for obj in self._source.list(kind):
                self._create(kind, obj)

    def run(self) -> "Syncer":
        """sync_once, then mirror watch events until stop()."""
        # Subscribe BEFORE listing so nothing between list and watch is
        # lost; duplicate ADDED events collapse through apply().
        stream = self._source.watch(self._kinds)
        try:
            self.sync_once()
        except BaseException:
            # A network-backed source's initial LIST can fail; the stream
            # already started reader threads that must not outlive us.
            stream.close()
            raise
        self._stop.clear()

        def loop() -> None:  # ksimlint: thread-role(service-loop)
            try:
                while not self._stop.is_set():
                    ev = stream.next(timeout=0.1)
                    if ev is None:
                        continue
                    if ev.event_type == ADDED:
                        self._create(ev.kind, ev.obj)
                    elif ev.event_type == MODIFIED:
                        self._update(ev.kind, ev.obj)
                    elif ev.event_type == DELETED:
                        self._delete(ev.kind, ev.obj)
            finally:
                stream.close()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
