"""Reset service: restore the boot-time cluster state and scheduler config.

The reference snapshots every etcd KV under its prefix at boot and
restores them (deleting everything else) on Reset, then resets the
scheduler configuration (reference simulator/reset/reset.go:33-85).  Here
the "etcd prefix" is the whole ClusterStore."""

from __future__ import annotations

from typing import Any

from ksim_tpu.state.cluster import ClusterStore


class ResetService:
    def __init__(self, store: ClusterStore, scheduler_service: Any = None) -> None:
        self._store = store
        self._sched = scheduler_service
        # Captured once at construction — the DI container builds this
        # after any one-shot import, like the reference's boot order
        # (cmd/simulator/simulator.go:104-113 imports BEFORE the DI
        # container snapshot is used... the reference snapshots at
        # NewResetService time, di.go:24-31).
        self._initial = store.dump()

    def reset(self) -> None:
        """Restore initial resources and reset the scheduler config."""
        self._store.restore(self._initial)
        if self._sched is not None:
            self._sched.reset_scheduler_config()
