"""Minimal built-in web UI.

The reference ships a ~5k-LoC Nuxt2/Vuetify app (reference web/) that is
a pure client of the REST + annotation contract; this single-file page
demonstrates that contract end-to-end against THIS server: live
node/pod tables fed by the streaming /api/v1/listwatchresources
endpoint, per-plugin Filter/Score/FinalScore tables decoded from the 13
result annotations (the SchedulingResults.vue analogue), and the
export/reset top-bar operations.  Served at / by SimulatorServer."""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>ksim-tpu simulator</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.2rem; }
  table { border-collapse: collapse; margin-top: .4rem; font-size: .85rem; }
  th, td { border: 1px solid #ccc; padding: .25rem .5rem; text-align: left; }
  th { background: #f3f3f3; }
  .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
          background: #e8f0fe; margin-right: .3rem; }
  .pending { background: #fde8e8; }
  button { margin-right: .6rem; }
  #results pre { background: #f8f8f8; padding: .5rem; overflow-x: auto; }
  tr.sel { background: #fffbe6; cursor: pointer; } tr[data-pod] { cursor: pointer; }
</style>
</head>
<body>
<h1>ksim-tpu scheduler simulator</h1>
<div>
  <button onclick="doExport()">Export snapshot</button>
  <button onclick="doReset()">Reset cluster</button>
  <span id="status" class="pill">connecting…</span>
</div>
<h2>Nodes (<span id="nodecount">0</span>)</h2>
<table id="nodes"><thead><tr><th>name</th><th>cpu</th><th>memory</th><th>pods</th></tr></thead><tbody></tbody></table>
<h2>Pods (<span id="podcount">0</span>)</h2>
<table id="pods"><thead><tr><th>namespace/name</th><th>node</th><th>phase</th><th>selected-node annotation</th></tr></thead><tbody></tbody></table>
<h2>Scheduling results <small>(click a pod)</small></h2>
<div id="results">none selected</div>
<script>
const nodes = new Map(), pods = new Map();
const PREFIX = "kube-scheduler-simulator.sigs.k8s.io/";
// All interpolated data is escaped: snapshots/extender results are
// untrusted input and reach this page via annotations.
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({
    "&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}

function render() {
  const nb = document.querySelector("#nodes tbody"); nb.innerHTML = "";
  for (const n of [...nodes.values()].sort((a,b)=>a.metadata.name.localeCompare(b.metadata.name))) {
    const a = (n.status||{}).allocatable||{};
    nb.insertAdjacentHTML("beforeend",
      `<tr><td>${esc(n.metadata.name)}</td><td>${esc(a.cpu||"")}</td><td>${esc(a.memory||"")}</td><td>${esc(a.pods||"")}</td></tr>`);
  }
  document.getElementById("nodecount").textContent = nodes.size;
  const pb = document.querySelector("#pods tbody"); pb.innerHTML = "";
  for (const [key,p] of [...pods.entries()].sort()) {
    const sel = ((p.metadata||{}).annotations||{})[PREFIX+"selected-node"]||"";
    const nn = (p.spec||{}).nodeName||"";
    pb.insertAdjacentHTML("beforeend",
      `<tr data-pod="${esc(key)}" class="${nn?"":"pending"}"><td>${esc(key)}</td><td>${esc(nn)}</td><td>${esc((p.status||{}).phase||"Pending")}</td><td>${esc(sel)}</td></tr>`);
  }
  document.getElementById("podcount").textContent = pods.size;
  for (const tr of document.querySelectorAll("tr[data-pod]"))
    tr.onclick = () => showResults(tr.dataset.pod);
}

function showResults(key) {
  const p = pods.get(key); if (!p) return;
  const annos = ((p.metadata||{}).annotations)||{};
  const cats = ["filter-result","score-result","finalscore-result","postfilter-result",
                "prefilter-result-status","prescore-result","selected-node","result-history"];
  let html = `<b>${esc(key)}</b>`;
  for (const c of cats) {
    const raw = annos[PREFIX+c]; if (raw === undefined) continue;
    let body = raw;
    try {
      const obj = JSON.parse(raw);
      if (c.endsWith("-result") && obj && typeof obj === "object" && !Array.isArray(obj)) {
        const nodesK = Object.keys(obj).sort();
        const plugins = [...new Set(nodesK.flatMap(n=>Object.keys(obj[n]||{})))].sort();
        if (plugins.length) {
          body = `<table><tr><th>node</th>${plugins.map(p=>`<th>${esc(p)}</th>`).join("")}</tr>` +
            nodesK.map(n=>`<tr><td>${esc(n)}</td>${plugins.map(pl=>`<td>${esc((obj[n]||{})[pl]??"")}</td>`).join("")}</tr>`).join("") +
            `</table>`;
        } else { body = `<pre>${esc(JSON.stringify(obj,null,1))}</pre>`; }
      } else { body = `<pre>${esc(JSON.stringify(obj,null,1))}</pre>`; }
    } catch (e) { body = `<pre>${esc(raw)}</pre>`; }
    html += `<h2>${esc(c)}</h2>${body}`;
  }
  document.getElementById("results").innerHTML = html;
}

async function watch() {
  const resp = await fetch("/api/v1/listwatchresources");
  document.getElementById("status").textContent = "live";
  const reader = resp.body.getReader();
  const dec = new TextDecoder(); let buf = "";
  for (;;) {
    const {value, done} = await reader.read();
    if (done) break;
    buf += dec.decode(value, {stream: true});
    let i;
    while ((i = buf.indexOf("\\n")) >= 0) {
      const line = buf.slice(0, i); buf = buf.slice(i+1);
      if (!line.trim()) continue;
      const ev = JSON.parse(line);
      const md = (ev.Obj||{}).metadata||{};
      const key = (md.namespace ? md.namespace+"/" : "") + md.name;
      const map = ev.Kind === "nodes" ? nodes : ev.Kind === "pods" ? pods : null;
      if (!map) continue;
      if (ev.EventType === "DELETED") map.delete(key); else map.set(key, ev.Obj);
    }
    render();
  }
  document.getElementById("status").textContent = "disconnected";
}

async function doExport() {
  const r = await fetch("/api/v1/export");
  const blob = await r.blob();
  const a = document.createElement("a");
  a.href = URL.createObjectURL(blob); a.download = "snapshot.json"; a.click();
}
async function doReset() {
  await fetch("/api/v1/reset", {method: "PUT"});
  nodes.clear(); pods.clear(); render();
}
watch();
</script>
</body>
</html>
"""
