"""Built-in web UI.

The reference ships a ~5k-LoC Nuxt2/Vuetify app (reference web/) that is
a pure client of the REST + annotation contract; this single-file page
covers that app's workflow against THIS server: live tables for all 7
resource kinds fed by the streaming /api/v1/listwatchresources endpoint,
a per-node pod board with an "unscheduled" bucket (the reference's
pods-by-node store, web/store/pod.ts:12-16,43-51), per-plugin
Filter/Score/FinalScore tables decoded from the 13 result annotations
with a result-history attempt browser (SchedulingResults.vue), resource
create from prefilled templates — pasted YAML manifests create too —
(ResourceAddButton.vue), view/edit of any live resource round-tripped
through the /api/v1/resources CRUD as YAML (default) or JSON (the
YamlEditor.vue + server-side-apply workflow, web/api/v1/pod.ts:22-53;
YAML conversion is server-side, http.py _yaml/_body), delete, a
scheduler-configuration editor with the same YAML/JSON toggle
(SchedulerConfigurationEditButton.vue), snapshot export/import and reset
(TopBar/), and a metrics panel.  Served at / by SimulatorServer."""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>ksim-tpu simulator</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 1.2rem; color: #222; }
  h1 { font-size: 1.2rem; } h2 { font-size: 1rem; margin-top: 1.1rem; }
  table { border-collapse: collapse; margin-top: .4rem; font-size: .85rem; }
  th, td { border: 1px solid #ccc; padding: .22rem .5rem; text-align: left; }
  th { background: #f3f3f3; }
  .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
          background: #e8f0fe; margin-right: .3rem; }
  .pending { background: #fde8e8; }
  button { margin-right: .4rem; }
  #results pre, #metrics pre, pre { background: #f8f8f8; padding: .5rem; overflow-x: auto; }
  tr[data-pod] { cursor: pointer; }
  .tab { cursor: pointer; padding: .2rem .7rem; border: 1px solid #ccc;
         border-bottom: none; display: inline-block; background: #f3f3f3; }
  .tab.active { background: #fff; font-weight: 600; }
  textarea { width: 100%; min-height: 10rem; font-family: monospace; }
  .panel { border: 1px solid #ccc; padding: .6rem; margin-top: .4rem; }
  .del, .edit { color: #a00; cursor: pointer; }
  .edit { color: #06c; margin-right: .5rem; }
  #board { display: flex; flex-wrap: wrap; gap: .6rem; margin-top: .4rem; }
  .bucket { border: 1px solid #ccc; border-radius: 4px; padding: .4rem .6rem;
            min-width: 11rem; vertical-align: top; background: #fafafa; }
  .bucket h3 { margin: 0 0 .3rem; font-size: .9rem; }
  .bucket.unsched { background: #fff4f4; }
  .bpod { display: block; cursor: pointer; font-size: .85rem; padding: .05rem 0; }
  .bpod:hover { text-decoration: underline; }
  .attempt { cursor: pointer; padding: .1rem .5rem; border: 1px solid #ccc;
             display: inline-block; margin-right: .25rem; background: #f3f3f3; }
  .attempt.active { background: #fff; font-weight: 600; }
</style>
</head>
<body>
<h1>ksim-tpu scheduler simulator</h1>
<div>
  <button onclick="doExport()">Export snapshot</button>
  <button onclick="importFile.click()">Import snapshot</button>
  <input type="file" id="importFile" style="display:none" onchange="doImport(this)"/>
  <button onclick="doReset()">Reset cluster</button>
  <button onclick="toggle('config', loadConfig)">Scheduler config</button>
  <button onclick="toggle('metrics', loadMetrics)">Metrics</button>
  <button onclick="toggle('boardPanel', renderBoard)">Pod board</button>
  <span id="status" class="pill">connecting…</span>
</div>

<div id="config" class="panel" style="display:none">
  <b>KubeSchedulerConfiguration</b> (applying compiles the new
  kernel set — the reference's scheduler restart)
  <span id="configFmtBtns"></span><br/>
  <textarea id="configText"></textarea><br/>
  <button onclick="applyConfig()">Apply</button>
  <button onclick="loadConfig()">Reload current</button>
  <span id="configMsg"></span>
</div>

<div id="metrics" class="panel" style="display:none"><pre id="metricsPre"></pre></div>

<div id="boardPanel" class="panel" style="display:none">
  <b>Pods by node</b> (unscheduled bucket first — web/store/pod.ts)
  <div id="board"></div>
</div>

<div style="margin-top:1rem" id="tabs"></div>
<div class="panel" id="tabpanel">
  <div>
    <b id="kindTitle"></b>
    <button onclick="showAdd()">Add…</button>
    <span id="kindCount" class="pill"></span>
  </div>
  <div id="addPanel" style="display:none">
    <textarea id="addText"></textarea><br/>
    <button onclick="doAdd()">Create</button>
    <span id="addMsg"></span>
  </div>
  <div id="editPanel" style="display:none">
    <b>Edit <span id="editKey"></span></b> (live object; Save PUTs it back)
    <span id="editFmtBtns"></span><br/>
    <textarea id="editText"></textarea><br/>
    <button onclick="doSave()">Save</button>
    <button onclick="hideEdit()">Cancel</button>
    <span id="editMsg"></span>
  </div>
  <table id="resTable"><thead></thead><tbody></tbody></table>
</div>

<h2>Scheduling results <small>(click a pod row)</small></h2>
<div id="results">none selected</div>

<script>
const PREFIX = "kube-scheduler-simulator.sigs.k8s.io/";
const KINDS = ["pods","nodes","persistentvolumes","persistentvolumeclaims",
               "storageclasses","priorityclasses","namespaces"];
const store = Object.fromEntries(KINDS.map(k => [k, new Map()]));
let activeKind = "pods";
let selectedPod = null;
let selectedAttempt = -1;  // -1 = latest (live annotations)

// New-resource templates (the reference's web/components/lib/templates).
const TEMPLATES = {
  pods: {metadata:{name:"pod-new",namespace:"default"},spec:{containers:[
    {name:"c",image:"registry.k8s.io/pause:3.9",resources:{requests:{cpu:"100m",memory:"128Mi"}}}]}},
  nodes: {metadata:{name:"node-new"},status:{allocatable:{cpu:"4",memory:"8Gi",pods:"110"},
    capacity:{cpu:"4",memory:"8Gi",pods:"110"}}},
  persistentvolumes: {metadata:{name:"pv-new"},spec:{capacity:{storage:"1Gi"},
    accessModes:["ReadWriteOnce"],persistentVolumeReclaimPolicy:"Delete"},status:{phase:"Available"}},
  persistentvolumeclaims: {metadata:{name:"pvc-new",namespace:"default"},spec:{
    accessModes:["ReadWriteOnce"],resources:{requests:{storage:"1Gi"}}}},
  storageclasses: {metadata:{name:"sc-new"},provisioner:"kubernetes.io/no-provisioner",
    volumeBindingMode:"WaitForFirstConsumer"},
  priorityclasses: {metadata:{name:"pc-new"},value:1000},
  namespaces: {metadata:{name:"ns-new"}},
};

// All interpolated data is escaped: snapshots/annotations are untrusted.
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({
    "&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
}
function keyOf(obj) {
  const md = (obj||{}).metadata||{};
  return (md.namespace ? md.namespace+"/" : "") + md.name;
}

function renderTabs() {
  document.getElementById("tabs").innerHTML = KINDS.map(k =>
    `<span class="tab ${k===activeKind?"active":""}" onclick="setKind('${k}')">${k} (${store[k].size})</span>`).join("");
}
function setKind(k) {
  activeKind = k;
  document.getElementById("addPanel").style.display = "none";
  hideEdit();
  render();
}

const COLS = {
  pods: ["node", "phase", "selected-node"],
  nodes: ["cpu", "memory", "pods", "unschedulable"],
  persistentvolumes: ["capacity", "phase", "claimRef"],
  persistentvolumeclaims: ["volumeName", "storageClassName"],
  storageclasses: ["provisioner", "bindingMode"],
  priorityclasses: ["value"],
  namespaces: [],
};
function cols(kind, o) {
  const md = o.metadata||{}, spec = o.spec||{}, st = o.status||{};
  switch (kind) {
    case "pods": return [spec.nodeName||"", st.phase||"Pending",
      ((md.annotations||{})[PREFIX+"selected-node"])||""];
    case "nodes": { const a = st.allocatable||{};
      return [a.cpu||"", a.memory||"", a.pods||"", spec.unschedulable?"true":""]; }
    case "persistentvolumes": return [((spec.capacity||{}).storage)||"", st.phase||"",
      spec.claimRef ? keyOf({metadata:spec.claimRef}) : ""];
    case "persistentvolumeclaims": return [spec.volumeName||"", spec.storageClassName||""];
    case "storageclasses": return [o.provisioner||"", o.volumeBindingMode||""];
    case "priorityclasses": return [String(o.value ?? "")];
    default: return [];
  }
}

function render() {
  renderTabs();
  const kind = activeKind;
  document.getElementById("kindTitle").textContent = kind;
  document.getElementById("kindCount").textContent = store[kind].size + " objects";
  const head = ["name", ...COLS[kind], ""].map(c=>`<th>${esc(c)}</th>`).join("");
  document.querySelector("#resTable thead").innerHTML = `<tr>${head}</tr>`;
  const tb = document.querySelector("#resTable tbody"); tb.innerHTML = "";
  for (const [key, o] of [...store[kind].entries()].sort()) {
    const extra = cols(kind, o).map(v=>`<td>${esc(v)}</td>`).join("");
    const podAttr = kind === "pods" ? ` data-pod="${esc(key)}"` : "";
    const cls = kind === "pods" && !(o.spec||{}).nodeName ? ' class="pending"' : "";
    tb.insertAdjacentHTML("beforeend",
      `<tr${podAttr}${cls}><td>${esc(key)}</td>${extra}` +
      `<td><span class="edit" data-key="${esc(key)}">edit</span>` +
      `<span class="del" data-key="${esc(key)}">delete</span></td></tr>`);
  }
  // Handlers read dataset values — never inline JS with interpolated
  // strings (entity escaping is undone before the JS engine parses an
  // inline handler, which would turn a crafted resource name into
  // stored script injection).
  for (const el of document.querySelectorAll(".del"))
    el.onclick = (ev) => { ev.stopPropagation(); doDelete(el.dataset.key); };
  for (const el of document.querySelectorAll(".edit"))
    el.onclick = (ev) => { ev.stopPropagation(); showEdit(el.dataset.key); };
  for (const tr of document.querySelectorAll("tr[data-pod]"))
    tr.onclick = () => showResults(tr.dataset.pod);
  if (document.getElementById("boardPanel").style.display !== "none") renderBoard();
}

// -- pods-by-node board (web/store/pod.ts:12-16,43-51) ----------------------

// Permit-parked pods (assumed on their node but not bound yet): cached
// map refreshed with a single-in-flight fetch; renderBoard itself stays
// synchronous so overlapping watch chunks can't interleave stale
// responses over newer board states.
let waitingMap = new Map();
let waitingFetch = null;
function refreshWaiting() {
  if (waitingFetch) return;
  waitingFetch = fetch("/api/v1/waitingpods")
    .then(r => r.json())
    .then(out => {
      waitingMap = new Map(
        (out.items || []).map(w => [keyOf({metadata: w}), w.nodeName]));
      waitingFetch = null;
      renderBoardNow();
    })
    .catch(() => { waitingFetch = null; });
}

function renderBoard() {
  refreshWaiting();
  renderBoardNow();
}

function renderBoardNow() {
  const waiting = waitingMap;
  const buckets = new Map([["unscheduled", []]]);
  for (const name of [...store.nodes.keys()].sort()) buckets.set(name, []);
  for (const [key, p] of [...store.pods.entries()].sort()) {
    const node = (p.spec||{}).nodeName || waiting.get(key) || "unscheduled";
    if (!buckets.has(node)) buckets.set(node, []);
    buckets.get(node).push(key);
  }
  let html = "";
  for (const [node, podKeys] of buckets) {
    const cls = node === "unscheduled" ? "bucket unsched" : "bucket";
    html += `<div class="${cls}"><h3>${esc(node)} (${podKeys.length})</h3>` +
      podKeys.map(k=>{
        const tag = waiting.has(k) ? " ⏳" : "";
        return `<span class="bpod" data-pod="${esc(k)}">${esc(k)}${tag}</span>`;
      }).join("") +
      `</div>`;
  }
  const board = document.getElementById("board");
  board.innerHTML = html;
  for (const el of board.querySelectorAll(".bpod"))
    el.onclick = () => showResults(el.dataset.pod);
}

// -- scheduling results + history browser (SchedulingResults.vue) -----------

const RESULT_CATS = ["filter-result","score-result","finalscore-result","postfilter-result",
  "prefilter-result-status","prescore-result","reserve-result","permit-result",
  "permit-result-timeout","bind-result","selected-node"];

function categoryHTML(c, raw) {
  if (raw === undefined) return "";
  let body;
  try {
    const obj = JSON.parse(raw);
    if (c.endsWith("-result") && obj && typeof obj === "object" && !Array.isArray(obj)) {
      const nodesK = Object.keys(obj).sort();
      const plugins = [...new Set(nodesK.flatMap(n=>
        (obj[n] && typeof obj[n] === "object") ? Object.keys(obj[n]) : []))].sort();
      if (plugins.length && nodesK.every(n=>obj[n] && typeof obj[n] === "object")) {
        body = `<table><tr><th>node</th>${plugins.map(p=>`<th>${esc(p)}</th>`).join("")}</tr>` +
          nodesK.map(n=>`<tr><td>${esc(n)}</td>${plugins.map(pl=>`<td>${esc((obj[n]||{})[pl]??"")}</td>`).join("")}</tr>`).join("") +
          `</table>`;
      } else { body = `<pre>${esc(JSON.stringify(obj,null,1))}</pre>`; }
    } else { body = `<pre>${esc(JSON.stringify(obj,null,1))}</pre>`; }
  } catch (e) { body = `<pre>${esc(raw)}</pre>`; }
  return `<h2>${esc(c)}</h2>${body}`;
}

function showResults(key, attempt = -1) {
  selectedPod = key; selectedAttempt = attempt;
  const p = store.pods.get(key);
  if (!p) { document.getElementById("results").innerHTML = "none selected"; return; }
  const annos = ((p.metadata||{}).annotations)||{};
  let history = [];
  try { history = JSON.parse(annos[PREFIX+"result-history"] || "[]"); } catch (e) {}
  let html = `<b>${esc(key)}</b>`;
  // Attempt selector: the result-history annotation holds every past
  // attempt's full result set (storereflector.go:148-167).
  if (history.length > 1 || (history.length === 1 && attempt >= 0)) {
    html += `<div style="margin:.3rem 0">history: ` + history.map((_, i) =>
      `<span class="attempt ${i===attempt?"active":""}" data-attempt="${i}">#${i+1}</span>`
    ).join("") +
    `<span class="attempt ${attempt<0?"active":""}" data-attempt="-1">latest</span></div>`;
  }
  const source = attempt >= 0 && history[attempt]
    ? history[attempt]
    : annos;
  for (const c of RESULT_CATS) html += categoryHTML(c, source[PREFIX+c]);
  if (attempt < 0 && history.length)
    html += `<h2>attempts recorded</h2><pre>${esc(String(history.length))}</pre>`;
  const el = document.getElementById("results");
  el.innerHTML = html;
  for (const a of el.querySelectorAll(".attempt"))
    a.onclick = () => showResults(key, parseInt(a.dataset.attempt, 10));
}

// Per-kind last seen resourceVersion for reconnect-with-resume (the
// reference's RetryWatcher behavior on the client side).  The param map
// is interpolated from the server's single source of truth
// (ksim_tpu/server/params.py).
const LRV_PARAM = __LRV_PARAMS_JSON__;
const lastRV = {};

async function watch() {
  for (;;) {
    let resumed = false;
    try {
      const params = Object.entries(lastRV)
        .map(([k, rv]) => `${LRV_PARAM[k]}=${rv}`).join("&");
      const resp = await fetch("/api/v1/listwatchresources" + (params ? `?${params}` : ""));
      if (resp.status === 410) {
        // Compacted resume point: drop caches and relist from scratch.
        for (const k of KINDS) { store[k].clear(); delete lastRV[k]; }
        render();
        continue;
      }
      document.getElementById("status").textContent = "live";
      resumed = true;
      const reader = resp.body.getReader();
      const dec = new TextDecoder(); let buf = "";
      for (;;) {
        const {value, done} = await reader.read();
        if (done) break;
        buf += dec.decode(value, {stream: true});
        let i;
        while ((i = buf.indexOf("\\n")) >= 0) {
          const line = buf.slice(0, i); buf = buf.slice(i+1);
          if (!line.trim()) continue;
          const ev = JSON.parse(line);
          const map = store[ev.Kind]; if (!map) continue;
          const key = keyOf(ev.Obj);
          if (ev.EventType === "DELETED") map.delete(key); else map.set(key, ev.Obj);
          const rv = parseInt(((ev.Obj||{}).metadata||{}).resourceVersion, 10);
          if (!isNaN(rv)) lastRV[ev.Kind] = rv;
          if (ev.Kind === "pods" && key === selectedPod) showResults(key, selectedAttempt);
        }
        render();
      }
    } catch (e) { console.error("watch stream error", e); }
    document.getElementById("status").textContent = "reconnecting…";
    if (!resumed) {
      // Repeated failures without ever connecting: full refresh next try.
      for (const k of KINDS) { store[k].clear(); delete lastRV[k]; }
    }
    await new Promise(r => setTimeout(r, 1500));
  }
}

function resourcePath(kind, key) {
  const [a, b] = key.includes("/") ? key.split("/") : [null, key];
  return `/api/v1/resources/${kind}/` + (a ? `${a}/${b}` : b);
}
async function doDelete(key) {
  await fetch(resourcePath(activeKind, key), {method: "DELETE"});
}
function showAdd() {
  const t = document.getElementById("addText");
  t.value = JSON.stringify(TEMPLATES[activeKind], null, 1);
  document.getElementById("addPanel").style.display = "block";
  document.getElementById("addMsg").textContent = "";
}
async function doAdd() {
  const msg = document.getElementById("addMsg");
  try {
    // Paste-a-manifest workflow: JSON if it parses, otherwise the text
    // POSTs as YAML and the server parses it.
    const text = document.getElementById("addText").value;
    let body = text, ctype = "application/yaml";
    try { body = JSON.stringify(JSON.parse(text)); ctype = "application/json"; }
    catch (e) {}
    const r = await fetch(`/api/v1/resources/${activeKind}`, {
      method: "POST", headers: {"Content-Type": ctype}, body});
    msg.textContent = r.ok ? "created" : `error ${r.status}: ${await r.text()}`;
    if (r.ok) document.getElementById("addPanel").style.display = "none";
  } catch (e) { msg.textContent = String(e); }
}

// -- view/edit any live resource (the YamlEditor.vue workflow: YAML is
// the default editing format, server-side converted; JSON one click away) ---

let editTarget = null;  // {kind, key}
let editFmt = "yaml";

function fmtButtons(spanId, current, onPick) {
  const span = document.getElementById(spanId);
  span.innerHTML = ["yaml", "json"].map(f =>
    `<span class="tab ${f===current?"active":""}" data-fmt="${f}">${f}</span>`).join("");
  for (const el of span.querySelectorAll(".tab"))
    el.onclick = () => onPick(el.dataset.fmt);
}

async function showEdit(key, fmt, kindOverride) {
  // The format toggle re-invokes with the ORIGINAL kind: activeKind may
  // have moved to another tab while the edit panel stayed open.
  const kind = kindOverride || activeKind;
  editFmt = fmt || editFmt;
  const msg = document.getElementById("editMsg");
  try {
    const q = editFmt === "yaml" ? "?format=yaml" : "";
    const r = await fetch(resourcePath(kind, key) + q);
    if (!r.ok) { msg.textContent = `load failed: ${r.status}`; return; }
    editTarget = {kind, key};
    document.getElementById("editKey").textContent = `${kind}/${key}`;
    document.getElementById("editText").value = editFmt === "yaml"
      ? await r.text()
      : JSON.stringify(await r.json(), null, 1);
    fmtButtons("editFmtBtns", editFmt, f => {
      if (editTarget) showEdit(editTarget.key, f, editTarget.kind);
    });
    document.getElementById("editPanel").style.display = "block";
    msg.textContent = "";
  } catch (e) { msg.textContent = String(e); }
}
function hideEdit() {
  editTarget = null;
  document.getElementById("editPanel").style.display = "none";
}
async function doSave() {
  const msg = document.getElementById("editMsg");
  if (!editTarget) return;
  try {
    const text = document.getElementById("editText").value;
    let body = text, ctype = "application/yaml";
    if (editFmt === "json") {
      body = JSON.stringify(JSON.parse(text));
      ctype = "application/json";
    }
    const r = await fetch(resourcePath(editTarget.kind, editTarget.key), {
      method: "PUT", headers: {"Content-Type": ctype}, body});
    msg.textContent = r.ok ? "saved" : `rejected ${r.status}: ${await r.text()}`;
    if (r.ok) hideEdit();
  } catch (e) { msg.textContent = String(e); }
}

function toggle(id, onShow) {
  const el = document.getElementById(id);
  const show = el.style.display === "none";
  el.style.display = show ? "block" : "none";
  if (show && onShow) onShow();
}
let configFmt = "yaml";
async function loadConfig(fmt) {
  configFmt = fmt || configFmt;
  const q = configFmt === "yaml" ? "?format=yaml" : "";
  const r = await fetch("/api/v1/schedulerconfiguration" + q);
  document.getElementById("configText").value = configFmt === "yaml"
    ? await r.text()
    : JSON.stringify(await r.json(), null, 1);
  fmtButtons("configFmtBtns", configFmt, loadConfig);
  document.getElementById("configMsg").textContent = "";
}
async function applyConfig() {
  const msg = document.getElementById("configMsg");
  try {
    const text = document.getElementById("configText").value;
    let body = text, ctype = "application/yaml";
    if (configFmt === "json") {
      body = JSON.stringify(JSON.parse(text));
      ctype = "application/json";
    }
    const r = await fetch("/api/v1/schedulerconfiguration", {
      method: "POST", headers: {"Content-Type": ctype}, body});
    msg.textContent = r.ok ? "applied (kernel set recompiled)" : `rejected ${r.status}: ${await r.text()}`;
  } catch (e) { msg.textContent = String(e); }
}
async function loadMetrics() {
  const r = await fetch("/api/v1/metrics");
  document.getElementById("metricsPre").textContent = JSON.stringify(await r.json(), null, 1);
}

async function doExport() {
  const r = await fetch("/api/v1/export");
  const blob = await r.blob();
  const a = document.createElement("a");
  a.href = URL.createObjectURL(blob); a.download = "snapshot.json"; a.click();
}
async function doImport(input) {
  const file = input.files[0]; if (!file) return;
  await fetch("/api/v1/import", {method: "POST", body: await file.text(),
    headers: {"Content-Type": "application/json"}});
  input.value = "";
}
async function doReset() {
  await fetch("/api/v1/reset", {method: "PUT"});
  for (const k of KINDS) store[k].clear();
  render();
}
render();
watch();
</script>
</body>
</html>
"""

import json as _json

from ksim_tpu.server.params import LRV_PARAMS as _LRV_PARAMS

INDEX_HTML = INDEX_HTML.replace("__LRV_PARAMS_JSON__", _json.dumps(_LRV_PARAMS))
