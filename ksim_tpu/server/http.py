"""The simulator HTTP server (stdlib ThreadingHTTPServer).

Routes mirror the reference echo server exactly (reference
simulator/server/server.go:44-54, handlers under server/handler/):

    GET  /api/v1/schedulerconfiguration      -> current KubeSchedulerConfiguration
    POST /api/v1/schedulerconfiguration      -> apply (only .profiles/.extenders
                                                taken, schedulerconfig.go:42-64),
                                                202 on success, 500 on failure
    PUT  /api/v1/reset                       -> restore boot state, 202
    GET  /api/v1/export                      -> snapshot JSON (ResourcesForSnap)
    POST /api/v1/import                      -> load snapshot, 200
    GET  /api/v1/listwatchresources          -> streaming watch: newline-delimited
                                                {"Kind","EventType","Obj"} JSON
                                                (streamwriter.go:41-50); per-kind
                                                ?XXXlastResourceVersion= resumes
                                                (watcher.go:23-46)
    POST /api/v1/extender/{filter,prioritize,preempt,bind}/:id
                                             -> extender webhook proxy
                                                (server.go:88-93)

Beyond the reference surface: /api/v1/resources/* CRUD (the role the
KWOK apiserver plays for the reference UI), GET /api/v1/metrics (the
merged evidence document: scheduler counters + latency histograms +
fault-plane counters + replay driver stats + the job plane's queue/
worker/per-job section), GET /api/v1/trace (the
trace plane's event ring as Chrome trace-event JSON — see
docs/observability.md), the
Permit waiting-pod view/ops (GET /api/v1/waitingpods, POST
/api/v1/waitingpods/<ns>/<name>/{allow,reject} — the framework handle's
WaitingPod surface for external permit controllers), and the tenant
job plane (docs/jobs.md):

    POST   /api/v1/jobs                 -> submit a scenario job
                                           (202 {job}, 400 bad spec,
                                           413 over per-job bounds,
                                           429 queue full or tenant
                                           throttled — the throttle
                                           carries Retry-After)
    GET    /api/v1/jobs                 -> list job statuses
    GET    /api/v1/jobs/<id>            -> one job's status
    GET    /api/v1/jobs/<id>/result     -> final result document
                                           (409 until terminal)
    GET    /api/v1/jobs/<id>/events     -> SSE stream of progress +
                                           trace events (the
                                           listwatchresources chunked
                                           push pattern, SSE-framed)
    GET    /api/v1/jobs/<id>/trace      -> the JOB's private ring as
                                           Chrome trace JSON
    DELETE /api/v1/jobs/<id>            -> cancel (queued: immediate;
                                           running: cooperative, the
                                           in-flight segment rolls
                                           back)
    GET    /api/v1/traces               -> traces registered in the
                                           operator's KSIM_TRACES_DIR
                                           (what a tenant may reference
                                           as scenario source.trace.name
                                           — docs/scenario.md), with
                                           per-entry size_bytes / gzip /
                                           detected-format metadata

CORS headers come from ``cors_allowed_origins`` (the reference reads them
from config, server.go:28-32)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ksim_tpu.engine.compilecache import COMPILE_CACHE
from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import (
    TRACE,
    merge_chrome_traces,
    merge_fleet_docs,
    process_identity,
    provider_snapshots,
    read_fleet_snapshots,
    read_fleet_traces,
    render_prometheus,
)
from ksim_tpu.server.di import DIContainer

logger = logging.getLogger(__name__)

# Query-parameter names per kind (reference handler/watcher.go:26-34 —
# note the singular "namespace" prefix).
from ksim_tpu.server.params import LRV_PARAMS

EXTENDER_VERBS = ("filter", "prioritize", "preempt", "bind")


def _sse_heartbeat_s() -> float:
    """Idle bound before the job SSE stream emits a ``: keepalive``
    comment — ``KSIM_JOBS_SSE_HEARTBEAT_S`` (seconds, default 15; 0
    disables).  Proxies and LBs silently drop idle chunked responses;
    the comment line is invisible to EventSource consumers but keeps
    the connection (and the server's disconnect detection) live."""
    raw = os.environ.get("KSIM_JOBS_SSE_HEARTBEAT_S", "")
    try:
        return float(raw) if raw else 15.0
    except ValueError:
        return 15.0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "SimulatorServer"

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):  # route through logging, not stderr
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _cors(self) -> None:
        origins = self.server.cors_allowed_origins
        origin = self.headers.get("Origin")
        if origins and origin and (origin in origins or "*" in origins):
            self.send_header("Access-Control-Allow-Origin", origin)
            self.send_header("Access-Control-Allow-Credentials", "true")

    def _json(
        self, code: int, obj, headers: "dict[str, str] | None" = None
    ) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self._cors()
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _no_content(self, code: int) -> None:
        self.send_response(code)
        self._cors()
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _body(self):
        """Request body as an object.  JSON by default; a YAML
        Content-Type parses as YAML — the reference UI's lingua franca
        (its Monaco editors edit resources/config as YAML,
        web/components/ResourceBar/YamlEditor.vue), so pasted manifests
        round-trip without client-side conversion."""
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        if not raw:
            return {}
        if "yaml" in (self.headers.get("Content-Type") or ""):
            import yaml

            return yaml.safe_load(raw)
        return json.loads(raw)

    def _wants_yaml(self, query: dict | None) -> bool:
        fmt = (query or {}).get("format", [""])[0]
        return fmt == "yaml" or "yaml" in (self.headers.get("Accept") or "")

    def _yaml(self, code: int, obj) -> None:
        import yaml

        body = yaml.safe_dump(obj, sort_keys=False).encode()
        self.send_response(code)
        self._cors()
        self.send_header("Content-Type", "application/yaml; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _object(self, code: int, obj, query: dict | None = None) -> None:
        if self._wants_yaml(query):
            self._yaml(code, obj)
        else:
            self._json(code, obj)

    # -- chunked server push (listwatch + the job SSE stream) ---------------

    def _write_chunk(self, payload: bytes) -> bool:
        """One HTTP/1.1 chunk, flushed; False when the client is gone.
        Any OSError means gone — an aborted reader can surface as
        ETIMEDOUT/EPIPE wrapped in plain OSError, not just the two
        connection subclasses."""
        try:
            self.wfile.write(f"{len(payload):x}\r\n".encode() + payload + b"\r\n")
            self.wfile.flush()
            return True
        except OSError:
            return False

    def _end_chunks(self) -> None:
        """Graceful end-of-stream (the zero-length terminal chunk)."""
        try:
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass

    # -- routing ------------------------------------------------------------

    def do_OPTIONS(self) -> None:  # CORS preflight
        self.send_response(204)
        self._cors()
        self.send_header("Access-Control-Allow-Methods", "GET, POST, PUT, DELETE, OPTIONS")
        self.send_header("Access-Control-Allow-Headers", "Content-Type")
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self) -> None:
        url = urlparse(self.path)
        if url.path in ("/", "/index.html"):
            from ksim_tpu.server.ui import INDEX_HTML

            body = INDEX_HTML.encode()
            self.send_response(200)
            self._cors()
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif url.path == "/api/v1/schedulerconfiguration":
            self._object(
                200,
                self.server.di.scheduler_service.get_scheduler_config(),
                parse_qs(url.query),
            )
        elif url.path == "/api/v1/export":
            self._json(200, self.server.di.snapshot_service.snap())
        elif url.path == "/api/v1/metrics":
            # ?scope=fleet folds every published worker snapshot (plus
            # this process's live document) into one fleet document —
            # counters sum, histograms merge bucket-wise exactly, dead
            # workers surface flagged (docs/observability.md "Fleet
            # observability").
            if (parse_qs(url.query).get("scope") or [""])[0] == "fleet":
                self._json(200, self._fleet_metrics())
            else:
                self._json(200, self._merged_metrics())
        elif url.path == "/metrics":
            # Prometheus/OpenMetrics text exposition of the same
            # evidence (solo by default, ?scope=fleet for the merge).
            self._prometheus(parse_qs(url.query))
        elif url.path == "/api/v1/trace":
            # The live event ring as Chrome trace-event JSON — load the
            # response body straight into Perfetto (ui.perfetto.dev) or
            # chrome://tracing.  Empty unless the trace plane's ring is
            # on (KSIM_TRACE_OUT / KSIM_TRACE=1 / TRACE.enable()).
            # ?scope=fleet merges the frontdoor ring with every
            # published worker trace export: one process lane per
            # worker, flow arrows stitching submit -> claim -> run.
            if (parse_qs(url.query).get("scope") or [""])[0] == "fleet":
                self._json(200, self._fleet_trace())
            else:
                self._json(200, TRACE.export_chrome())
        elif url.path == "/api/v1/traces":
            # The named-trace registry (ksim_tpu/traces/registry.py):
            # names plus advisory metadata — resolution and parsing
            # stay server-side, and the detected format never overrides
            # the format a job spec names explicitly.
            from ksim_tpu.traces.registry import list_trace_entries

            self._json(200, {"items": list_trace_entries()})
        elif url.path == "/api/v1/waitingpods":
            # Permit-parked pods (the framework handle's waiting-pod view).
            self._json(200, {"items": self.server.di.scheduler_service.get_waiting_pods()})
        elif url.path == "/api/v1/listwatchresources":
            self._list_watch(parse_qs(url.query))
        elif url.path == "/api/v1/jobs" or url.path.startswith("/api/v1/jobs/"):
            self._job_get(url.path)
        elif url.path.startswith("/api/v1/resources/"):
            self._resource("GET", url.path, parse_qs(url.query))
        else:
            self._json(404, {"message": "Not Found"})

    def do_POST(self) -> None:
        url = urlparse(self.path)
        if url.path == "/api/v1/schedulerconfiguration":
            self._apply_scheduler_config()
        elif url.path == "/api/v1/jobs":
            self._job_submit()
        elif url.path == "/api/v1/import":
            try:
                self.server.di.snapshot_service.load(self._body())
            except Exception:
                logger.exception("failed to load snapshot")
                self._json(400, {"message": "Bad Request"})
                return
            self._no_content(200)
        elif url.path.startswith("/api/v1/extender/"):
            self._extender(url.path)
        elif url.path.startswith("/api/v1/waitingpods/"):
            self._waiting_pod_op(url.path)
        elif url.path.startswith("/api/v1/resources/"):
            self._resource("POST", url.path)
        else:
            self._json(404, {"message": "Not Found"})

    def _waiting_pod_op(self, path: str) -> None:
        """POST /api/v1/waitingpods/<ns>/<name>/{allow,reject} — the
        framework handle's WaitingPod.Allow/Reject over REST (an external
        permit controller's surface; in-process plugins use the service
        API directly)."""
        # Drain the request body FIRST, on every branch: the server keeps
        # HTTP/1.1 connections alive, and unread body bytes would parse as
        # the next request line on a pooled connection.
        try:
            body = self._body() or {}
        except Exception:
            body = {}
        parts = [p for p in path.split("/") if p]  # api v1 waitingpods ns name verb
        if len(parts) != 6 or parts[5] not in ("allow", "reject"):
            self._json(404, {"message": "Not Found"})
            return
        _api, _v1, _wp, ns, name, verb = parts
        svc = self.server.di.scheduler_service
        if verb == "allow":
            ok = svc.allow_waiting_pod(name, ns)
        else:
            ok = svc.reject_waiting_pod(
                name, ns, message=body.get("message") or "rejected"
            )
        if not ok:
            self._json(404, {"message": f"no waiting pod {ns}/{name}"})
            return
        self._json(200, {"status": "ok"})

    def do_PUT(self) -> None:
        url = urlparse(self.path)
        if url.path == "/api/v1/reset":
            try:
                self.server.di.reset_service.reset()
            except Exception:
                logger.exception("failed to reset")
                self._json(500, {"message": "Internal Server Error"})
                return
            self._no_content(202)
        elif url.path.startswith("/api/v1/resources/"):
            self._resource("PUT", url.path)
        else:
            self._json(404, {"message": "Not Found"})

    def do_DELETE(self) -> None:
        url = urlparse(self.path)
        if url.path.startswith("/api/v1/jobs/"):
            self._job_cancel(url.path)
        elif url.path.startswith("/api/v1/resources/"):
            self._resource("DELETE", url.path)
        else:
            self._json(404, {"message": "Not Found"})

    # -- handlers -----------------------------------------------------------

    def _merged_metrics(self) -> dict:
        """One GET = the whole degradation-evidence surface: the
        scheduler's counters + latency histograms, the trace plane's
        span histograms/event counters, every fault-plane site's
        calls/fired counters, the registered evidence providers
        (the live run's ``ReplayDriver.stats()`` under ``"replay"``,
        the process-wide ``compile_cache``), and the job plane's
        ``jobs`` section (queue depth, worker occupancy, per-job
        status + private-plane snapshots).  Previously only
        ``Metrics.snapshot()`` was served and the rest was visible
        only in bench JSON."""
        doc = self.server.di.scheduler_service.metrics.snapshot()
        doc["trace"] = TRACE.snapshot()
        doc["faults"] = FAULTS.snapshot()
        doc.update(provider_snapshots())
        # Present even before any replay ran (the import above also
        # registered it as a provider, so this is a no-op after one).
        doc.setdefault("compile_cache", COMPILE_CACHE.snapshot())
        # The jobs section reports WITHOUT forcing the worker pool into
        # existence: a server never asked to run a job shows the empty
        # shape, not two idle threads.
        jm = self.server.di.job_manager_if_built
        doc["jobs"] = (
            jm.snapshot()
            if jm is not None
            else {
                "queue": {
                    "depth": 0,
                    "capacity": 0,
                    "submitted": 0,
                    "rejected": 0,
                    "bypass_pops": 0,
                },
                "workers": {"pool": 0, "active": 0},
                "tenants": {},
                "jobs": {},
            }
        )
        # The process-identity block (role, worker_id, pid, started_at,
        # uptime_s) — unconditional: the fleet aggregator attributes
        # every snapshot to its producer through it.  Set LAST so no
        # provider can shadow it.
        doc["process"] = process_identity(
            role=jm.role if jm is not None else None,
            worker_id=jm.worker_id if jm is not None else None,
        )
        return doc

    def _fleet_metrics(self) -> dict:
        """``GET /api/v1/metrics?scope=fleet`` — every published worker
        snapshot under ``KSIM_JOBS_DIR/obs/`` plus THIS process's live
        document, folded by ``obs.merge_fleet_docs`` (the live document
        replaces this process's own published file, so the serving
        process is never reported stale to itself)."""
        jm = self.server.di.job_manager_if_built
        jobs_dir = getattr(jm, "jobs_dir", None)
        docs = read_fleet_snapshots(jobs_dir) if jobs_dir else {}
        live = self._merged_metrics()
        ident = live["process"]
        ident["published_at"] = round(time.time(), 3)
        docs[ident["worker_id"]] = live
        return merge_fleet_docs(docs)

    def _fleet_trace(self) -> dict:
        """``GET /api/v1/trace?scope=fleet`` — this process's ring (and
        its jobs' private rings) merged with every published worker
        trace export: one process lane per worker, submit->claim->run
        flow arrows across lanes (``obs.merge_chrome_traces``)."""
        jm = self.server.di.job_manager_if_built
        jobs_dir = getattr(jm, "jobs_dir", None)
        docs = read_fleet_traces(jobs_dir) if jobs_dir else {}
        wid = jm.worker_id if jm is not None else f"w{os.getpid()}"
        local = {wid: TRACE.export_chrome()}
        if jm is not None:
            for job in jm.jobs():
                plane = getattr(job, "trace", None)
                if plane is not None:
                    local[f"{wid}:{job.id}"] = plane.export_chrome()
        docs[wid] = (
            merge_chrome_traces(local) if len(local) > 1 else local[wid]
        )
        return merge_chrome_traces(docs, flows=True)

    def _prometheus(self, query: dict) -> None:
        """``GET /metrics`` — the evidence document as Prometheus text
        exposition (``?scope=fleet`` for the merged fleet document);
        every family name lives in the lint-enforced ``METRIC_NAMES``
        registry and the output round-trips through the in-repo
        ``obs.parse_prometheus`` validator in-suite."""
        scope = (query.get("scope") or [""])[0]
        doc = (
            self._fleet_metrics()
            if scope == "fleet"
            else self._merged_metrics()
        )
        body = render_prometheus(doc).encode()
        self.send_response(200)
        self._cors()
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- the job plane ------------------------------------------------------

    def _job_submit(self) -> None:
        """POST /api/v1/jobs: validate + enqueue a tenant scenario job.
        202 with the job status on success; 400 on a bad spec; 429 when
        the bounded queue refuses or the submitting tenant
        (``X-Ksim-Tenant`` header, else ``spec.tenant``) is over its
        quota/rate — the throttle response carries a ``Retry-After``
        header with the token bucket's computed wait."""
        from ksim_tpu.jobs import JobLimitExceeded, JobQueueFull, JobThrottled
        from ksim_tpu.scenario.spec import ScenarioSpecError

        try:
            doc = self._body()
        except Exception:
            self._json(400, {"message": "Bad Request"})
            return
        try:
            jm = self.server.di.job_manager
        except Exception:
            # Lazy construction can fail on operator config (e.g. a
            # malformed KSIM_JOBS_FAULTS) — that is a server-side 500,
            # not the tenant's spec, and must never escape the handler.
            logger.exception("job manager construction failed")
            self._json(500, {"message": "Internal Server Error"})
            return
        try:
            job = jm.submit(doc, tenant=self.headers.get("X-Ksim-Tenant"))
        except ScenarioSpecError as e:
            self._json(400, {"message": str(e)})
            return
        except JobLimitExceeded as e:
            # Payload-too-large, with the bound in the reason body so
            # the tenant can resize instead of guessing.
            self._json(413, {"message": str(e)})
            return
        except JobThrottled as e:
            # Retry-After is whole seconds (RFC 9110), rounded UP so an
            # obedient client never retries into the same empty bucket.
            self._json(
                429,
                {"message": str(e)},
                headers={"Retry-After": str(max(1, int(e.retry_after + 0.999)))},
            )
            return
        except JobQueueFull as e:
            self._json(429, {"message": str(e)})
            return
        except Exception:
            logger.exception("job submission failed")
            self._json(500, {"message": "Internal Server Error"})
            return
        self._json(202, job.status())

    def _job_parts(self, path: str) -> "tuple[str, str] | None":
        parts = [p for p in path.split("/") if p]  # api v1 jobs [id [sub]]
        if len(parts) == 3:
            return "", ""
        if len(parts) == 4:
            return parts[3], ""
        if len(parts) == 5 and parts[4] in ("result", "events", "trace"):
            return parts[3], parts[4]
        return None

    def _job_get(self, path: str) -> None:
        parsed = self._job_parts(path)
        if parsed is None:
            self._json(404, {"message": "Not Found"})
            return
        job_id, sub = parsed
        jm = self.server.di.job_manager_if_built
        if not job_id:
            self._json(
                200,
                {"items": [j.status() for j in jm.jobs()] if jm else []},
            )
            return
        job = jm.get(job_id) if jm else None
        if job is None:
            self._json(404, {"message": f"no job {job_id}"})
            return
        if sub == "":
            self._json(200, job.status())
        elif sub == "result":
            state, result, error = job.result_view()
            if state == "succeeded":
                self._json(200, {"id": job.id, "state": state, **(result or {})})
            elif state in ("failed", "cancelled", "interrupted"):
                self._json(
                    200,
                    {"id": job.id, "state": state, "phase": "Failed", "message": error},
                )
            else:
                self._json(
                    409, {"message": f"job {job_id} is {state}; result not ready"}
                )
        elif sub == "trace":
            # The JOB's private ring — the isolation story made visible:
            # only this tenant's spans/events, every record job-tagged.
            self._json(200, job.trace.export_chrome())
        else:  # events: the SSE stream
            self._job_events(job)

    def _job_events(self, job) -> None:  # ksimlint: thread-role(sse-handler)
        """Server push of one job's progress + trace events as
        Server-Sent Events on a flushed chunked response — the
        listwatchresources streaming pattern (eventproxy.go:66-80)
        wearing SSE framing, so a browser EventSource consumes it
        directly.  The event log replays from the start (late joiners
        see the whole history) and the stream ends after the terminal
        state event.

        Hardened (round 15): the listener is COUNTED on the job
        (``sse_listeners`` in the status document) and the count is
        released in a ``finally`` no matter how the reader goes away —
        an aborted EventSource must never leak a phantom listener.  An
        idle stream emits a ``: keepalive`` SSE comment every
        ``KSIM_JOBS_SSE_HEARTBEAT_S`` seconds, which both defeats
        idle-connection reaping by proxies and turns a silently dead
        socket into a detected disconnect (the chunk write fails)."""
        self.send_response(200)
        self._cors()
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        heartbeat_s = _sse_heartbeat_s()
        idx = 0
        last_write = time.monotonic()
        job.sse_attach()
        try:
            while not self.server.stopping.is_set():
                events, idx, done = job.events_since(idx, timeout=0.25)
                for ev in events:
                    if not self._write_chunk(
                        f"data: {json.dumps(ev)}\n\n".encode()
                    ):
                        return
                    last_write = time.monotonic()
                if done:
                    break
                if (
                    heartbeat_s > 0
                    and time.monotonic() - last_write >= heartbeat_s
                ):
                    if not self._write_chunk(b": keepalive\n\n"):
                        return
                    last_write = time.monotonic()
            self._end_chunks()
        finally:
            job.sse_detach()

    def _job_cancel(self, path: str) -> None:
        parsed = self._job_parts(path)
        if parsed is None or not parsed[0] or parsed[1]:
            self._json(404, {"message": "Not Found"})
            return
        jm = self.server.di.job_manager_if_built
        state = jm.cancel(parsed[0]) if jm else None
        if state is None:
            self._json(404, {"message": f"no job {parsed[0]}"})
            return
        self._json(200, {"id": parsed[0], "state": state})

    def _resource(self, method: str, path: str, query: dict | None = None) -> None:
        """Per-resource CRUD.  The reference UI talks straight to the
        KWOK kube-apiserver for this (web/api/v1/pod.ts etc.); the
        in-memory store takes that role here, so the simulator server
        exposes it:

        - ``GET /api/v1/resources/<kind>[?namespace=ns]`` — list (all
          namespaces unless filtered);
        - item routes: ``<kind>/<name>`` (cluster-scoped) or
          ``<kind>/<ns>/<name>`` (namespaced — both segments required);
        - ``POST <kind>`` create, ``PUT`` item update (path and body
          identity must agree, like the apiserver), ``DELETE`` item."""
        from ksim_tpu.errors import ConflictError, NotFoundError
        from ksim_tpu.state.cluster import KINDS, NAMESPACED_KINDS
        from ksim_tpu.state.resources import name_of, namespace_of

        parts = [p for p in path.split("/") if p]  # api, v1, resources, kind, ...
        kind = parts[3] if len(parts) > 3 else ""
        if kind not in KINDS:
            self._json(404, {"message": f"unknown kind {kind!r}"})
            return
        store = self.server.di.store
        rest = parts[4:]
        namespaced = kind in NAMESPACED_KINDS
        if namespaced and len(rest) == 1 and method != "POST":
            self._json(
                400,
                {"message": f"{kind} item routes need /{kind}/<namespace>/<name>"},
            )
            return
        namespace = rest[0] if namespaced and len(rest) == 2 else ""
        name = rest[-1] if rest else ""
        try:
            if method == "GET" and not name:
                ns_filter = (query or {}).get("namespace", [""])[0]
                self._object(200, {"items": store.list(kind, ns_filter)}, query)
            elif method == "GET":
                self._object(200, store.get(kind, name, namespace), query)
            elif method == "POST":
                self._json(201, store.create(kind, self._body()))
            elif method == "PUT":
                body = self._body()
                if name_of(body) != name or (
                    namespaced and (namespace_of(body) or "default") != namespace
                ):
                    self._json(
                        400,
                        {"message": "path and body name/namespace differ"},
                    )
                    return
                self._json(200, store.update(kind, body))
            elif method == "DELETE":
                store.delete(kind, name, namespace)
                self._no_content(200)
        except NotFoundError:
            self._json(404, {"message": "Not Found"})
        except ConflictError as e:
            self._json(409, {"message": str(e)})
        except Exception:
            logger.exception("resource %s %s failed", method, path)
            self._json(400, {"message": "Bad Request"})

    def _apply_scheduler_config(self) -> None:
        """Only .profiles and .extenders are taken from the payload
        (reference handler/schedulerconfig.go:42-64); failure to compile
        keeps the old config (RestartScheduler rollback) and returns 500."""
        try:
            req = self._body()
        except Exception:
            self._json(400, {"message": "Bad Request"})
            return
        svc = self.server.di.scheduler_service
        cfg = svc.get_scheduler_config()
        cfg["profiles"] = req.get("profiles") or []
        cfg["extenders"] = req.get("extenders") or []
        try:
            svc.apply_scheduler_config(cfg)
        except Exception:
            logger.exception("failed to apply scheduler config")
            self._json(500, {"message": "Internal Server Error"})
            return
        self._no_content(202)

    def _extender(self, path: str) -> None:
        parts = path.split("/")  # ['', 'api', 'v1', 'extender', verb, id]
        if len(parts) != 6 or parts[4] not in EXTENDER_VERBS:
            self._json(404, {"message": "Not Found"})
            return
        svc = self.server.di.extender_service
        if svc is None:
            self._json(400, {"message": "no extenders configured"})
            return
        try:
            idx = int(parts[5])
            if idx < 0:  # Python's negative indexing must not dispatch
                raise IndexError(idx)
            out = getattr(svc, parts[4])(idx, self._body())
        except (IndexError, ValueError):
            self._json(400, {"message": "Bad Request"})
            return
        except Exception:
            logger.exception("extender %s failed", parts[4])
            self._json(500, {"message": "Internal Server Error"})
            return
        self._json(200, out)

    def _list_watch(self, query: dict[str, list[str]]) -> None:
        """Server push: initial LIST as ADDED events for kinds without a
        lastResourceVersion, then live events, as newline-delimited JSON
        on a flushed chunked response (reference eventproxy.go:66-80,
        streamwriter.go:41-50)."""
        store = self.server.di.store
        since: dict[str, int] = {}
        listed: list[str] = []
        from ksim_tpu.state.cluster import KINDS, WatchEvent

        for kind in KINDS:
            raw = (query.get(LRV_PARAMS[kind]) or [""])[0]
            if raw:
                try:
                    since[kind] = int(raw)
                except ValueError:
                    listed.append(kind)
            else:
                listed.append(kind)

        # Atomic list+replay+subscribe under the store lock — no gap or
        # duplicate between the initial events and the live stream.  This
        # must happen BEFORE the 200 status goes out: a compacted resume
        # point answers 410 Gone (client drops its cache and relists).
        from ksim_tpu.errors import ExpiredError

        try:
            stream = store.watch(since=since, list_first=tuple(listed))
        except ExpiredError as e:
            self._json(410, {"message": str(e)})
            return

        self.send_response(200)
        self._cors()
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        try:
            while not self.server.stopping.is_set():
                ev = stream.next(timeout=0.25)
                if ev is None:
                    continue
                if not self._write_chunk(json.dumps(ev.to_json()).encode() + b"\n"):
                    return
            # Graceful end-of-stream on server shutdown.
            self._end_chunks()
        finally:
            stream.close()


class SimulatorServer(ThreadingHTTPServer):
    """The simulator's HTTP front end; serve_forever in a daemon thread
    via start(), stoppable via shutdown_server()."""

    daemon_threads = True

    def __init__(
        self,
        di: DIContainer,
        *,
        host: str = "127.0.0.1",
        port: int = 1212,
        cors_allowed_origins: tuple[str, ...] = (),
    ) -> None:
        super().__init__((host, port), _Handler)
        self.di = di
        self.cors_allowed_origins = tuple(cors_allowed_origins)
        self.stopping = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "SimulatorServer":
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown_server(self) -> None:
        self.stopping.set()
        self.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        self.server_close()
