"""DI container: the single place services are constructed and wired,
mirroring the reference (reference simulator/server/di/di.go:24-71)."""

from __future__ import annotations

import os
import threading
from typing import Any

from ksim_tpu.scheduler.service import SchedulerService
from ksim_tpu.server.reset import ResetService
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.snapshot import SnapshotService


class DIContainer:
    def __init__(
        self,
        store: ClusterStore | None = None,
        *,
        scheduler_config: dict | None = None,
        registry: dict | None = None,
        record: str = "full",
        start_scheduler: bool = False,
        scheduler_config_path: str | None = None,
    ) -> None:
        self.store = store if store is not None else ClusterStore()
        self.scheduler_service = SchedulerService(
            self.store,
            config=scheduler_config,
            registry=registry,
            record=record,
            config_path=scheduler_config_path,
        )
        self.snapshot_service = SnapshotService(
            self.store, scheduler_service=self.scheduler_service
        )
        self.reset_service = ResetService(self.store, self.scheduler_service)
        # The tenant job plane (ksim_tpu/jobs) is built LAZILY on first
        # use: constructing it spawns the worker pool, which a container
        # serving only the classic single-cluster surface never needs.
        self._job_manager = None
        self._job_manager_lock = threading.Lock()
        if os.environ.get("KSIM_JOBS_DIR"):
            # The durable job plane (docs/jobs.md "Durability &
            # recovery") replays its journal at CONSTRUCTION: a
            # restarted server must know its journaled jobs before the
            # first tenant GET, so the lazy build — a classic-surface
            # optimization — would leave recovered results 404 until
            # some request happened to force the manager into being.
            from ksim_tpu.jobs import JobManager

            self._job_manager = JobManager()
        if start_scheduler:
            self.scheduler_service.start()

    @property
    def job_manager(self):
        """The job plane (ksim_tpu/jobs.JobManager), built on first
        access from the job-plane environment knobs (docs/env.md
        "Job plane")."""
        with self._job_manager_lock:
            if self._job_manager is None:
                from ksim_tpu.jobs import JobManager

                self._job_manager = JobManager()
            return self._job_manager

    @property
    def job_manager_if_built(self):
        """The job plane if anything has used it yet, else None (the
        metrics endpoint reports without forcing worker threads into
        existence)."""
        with self._job_manager_lock:
            return self._job_manager

    @property
    def extender_service(self) -> Any:
        """The proxy behind /api/v1/extender/<verb>/<id> (server.go:88-93);
        follows the scheduler config (extenders live in
        KubeSchedulerConfiguration.extenders)."""
        svc = self.scheduler_service.extender_service
        return svc if svc else None

    def shutdown(self, timeout: "float | None" = 5.0) -> None:
        """Stop services.  Callers about to EXIT the process should pass a
        generous (or None) timeout: an abandoned loop thread alive during
        runtime teardown can corrupt the heap (SchedulerService.stop)."""
        jm = self.job_manager_if_built
        if jm is not None:
            jm.shutdown(timeout=timeout)
        self.scheduler_service.stop(timeout=timeout)
