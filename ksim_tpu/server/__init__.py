"""Simulator HTTP server: the product's API surface.

Mirrors the reference's echo server route-for-route (reference
simulator/server/server.go:44-54) over the in-memory ClusterStore and the
batch-evaluating scheduler service."""

from ksim_tpu.server.di import DIContainer
from ksim_tpu.server.http import SimulatorServer
from ksim_tpu.server.reset import ResetService

__all__ = ["DIContainer", "ResetService", "SimulatorServer"]
