"""Shared HTTP-contract constants.

The per-kind lastResourceVersion query parameters match the reference's
watcher handler (reference simulator/server/handler/watcher.go:23-46);
both the server route (server/http.py) and the built-in UI's reconnect
logic (server/ui.py) consume this one map.
"""

LRV_PARAMS = {
    "pods": "podsLastResourceVersion",
    "nodes": "nodesLastResourceVersion",
    "persistentvolumes": "pvsLastResourceVersion",
    "persistentvolumeclaims": "pvcsLastResourceVersion",
    "storageclasses": "scsLastResourceVersion",
    "priorityclasses": "pcsLastResourceVersion",
    "namespaces": "namespaceLastResourceVersion",
}
