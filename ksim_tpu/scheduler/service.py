"""The debuggable-scheduler loop over an in-memory cluster.

The reference runs a real kube-scheduler whose wrapped plugins record
results, then a store reflector copies them onto the Pod's annotations
(reference simulator/scheduler/plugin/wrappedplugin.go,
simulator/scheduler/storereflector/storereflector.go:78-146).  Here the
whole cycle is one service over the ClusterStore:

- watch pods/nodes; on relevant changes collect the pending queue
  (no ``spec.nodeName``, non-terminal, matching schedulerName — upstream
  only schedules pods addressed to one of its profiles);
- sort by priority desc then creation/name (upstream PrioritySort
  queue-sort semantics);
- featurize the snapshot, run the Engine's sequential-commit scan;
- for each pod, bind (set ``spec.nodeName``, phase Running — what KWOK's
  fake kubelet would do in the reference topology, compose.yml
  simulator-cluster) and write the 13 result annotations + result-history
  (engine/annotations.py), exactly as the reflector does.

Self-triggering guard: our own pod updates emit MODIFIED events; the run
loop skips events whose resourceVersion we just wrote, so an unschedulable
pod doesn't retrigger an identical cycle forever (the upstream analogue is
the scheduling queue's backoff, not event-driven retry).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Sequence

import copy

from ksim_tpu.engine import Engine
from ksim_tpu.engine.annotations import RenderCtx, apply_results_to_pod, render_pod_results
from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.faults import FAULTS
from ksim_tpu.scheduler.profile import (
    DEFAULT_SCHEDULER_NAME,
    Builder,
    CompiledProfile,
    compile_configuration,
)
from ksim_tpu.scheduler.permit import (
    REJECT,
    SUCCESS,
    WAIT,
    PermitResult,
    go_duration_str,
)
from ksim_tpu.errors import NotFoundError
from ksim_tpu.obs import TRACE
from ksim_tpu.state.cluster import ClusterStore, WatchEvent
from ksim_tpu.state.featurizer import FeaturizedSnapshot, Featurizer
from ksim_tpu.state.resources import JSON, name_of, namespace_of
from ksim_tpu.util import Metrics

logger = logging.getLogger(__name__)

PluginsFactory = Callable[[FeaturizedSnapshot], Sequence[ScoredPlugin]]

# Self-triggered-event suppression set cap (resourceVersions are numeric
# strings from ClusterStore; keep the newest).
_OWN_RV_LIMIT = 4096


def queue_sort_key(pod: JSON, priority_of=None):
    """Upstream PrioritySort: priority desc, then creation time asc; name
    breaks exact ties deterministically.  ``priority_of`` resolves
    PriorityClass names (state/priorities.py); bare spec.priority
    otherwise."""
    if priority_of is not None:
        prio = priority_of(pod)
    else:
        prio = int(pod.get("spec", {}).get("priority") or 0)
    created = pod.get("metadata", {}).get("creationTimestamp") or ""
    return (-prio, created, namespace_of(pod), name_of(pod))


from dataclasses import dataclass, field


@dataclass
class _WaitingPod:
    """A pod parked by Permit Wait (upstream framework waitingPod)."""

    name: str
    namespace: str
    node_name: str
    # plugin name -> monotonic deadline; emptied by allow() calls.
    pending: dict[str, float]
    # Pre-rendered result annotations (written at resolution).
    anno: dict[str, str] = field(default_factory=dict)
    # The pass's plugin tuple + compiled profile: the PreBind/Bind/
    # PostBind chains run at allow time (upstream: after WaitOnPermit).
    plugins: tuple = ()
    prof: object = None


class SchedulerService:
    """Batch-evaluating scheduler bound to a ClusterStore."""

    def __init__(
        self,
        store: ClusterStore,
        *,
        plugins_factory: PluginsFactory | None = None,
        config: JSON | None = None,
        registry: dict[str, Builder] | None = None,
        record: str = "full",
        featurizer: Featurizer | None = None,
        preemption: bool = True,
        max_pods_per_pass: int | None = None,
        pod_bucket_min: int | None = None,
        config_path: str | None = None,
        allow_plugin_imports: bool | None = None,
        shard_mesh=None,
    ) -> None:
        self._store = store
        # Preemption-eviction observers (add_eviction_listener): notified
        # with (namespace, name) right AFTER a victim's successful store
        # delete, so a live write-back can distinguish engine evictions
        # (which must propagate to the real cluster) from reset/user
        # deletes (which must never touch it).
        self._eviction_listeners: list = []
        # Optional jax.sharding.Mesh: every engine this service builds is
        # laid out over it (node axis over "tp", engine/sharding.py).  The
        # sequential scan wants replicated pod rows — pass a dp=1 mesh
        # (make_mesh(n, dp=1)) for the scheduling path.  The device
        # churn replay honors the same mesh (round 17): a dp=1 mesh
        # with a tp axis shards the segment scan's node tensors; any
        # other shape is a "shard_mesh" per-pass fallback.  On a fleet
        # lane (round 19) the mesh declares the node-shard WIDTH only:
        # the group dispatch composes that tp with KSIM_FLEET_DP on its
        # own (dp, tp) fleet mesh — lanes over dp, node shards over tp
        # (engine/replay.py service_supported, engine/fleet.py
        # _worker_mesh).
        self._shard_mesh = shard_mesh
        # builderImport in runtime-applied configs (HTTP / snapshot load)
        # executes arbitrary imports; off unless the operator opts in.
        if allow_plugin_imports is None:
            allow_plugin_imports = (
                os.environ.get("KSIM_ALLOW_PLUGIN_IMPORTS") == "1"
            )
        self._allow_plugin_imports = allow_plugin_imports
        # Deferred below: the boot-time apply must NOT rewrite the user's
        # file (the reference only rewrites on update calls).
        self._config_path = None
        self._registry = registry or {}
        self._record = record
        self._preemption = preemption
        # Fleet-lane attribution (engine/fleet.py): when this service
        # belongs to one trajectory of an S-lane fleet, its scheduling
        # spans carry the lane id so Chrome traces stay attributable.
        self._trace_lane: "int | None" = None
        # Upstream schedules ONE pod per cycle; a pass here batches the
        # queue.  Capping the batch bounds featurize/scan cost per pass
        # under churn saturation — excess pods are simply deeper in the
        # queue, exactly as upstream's one-at-a-time loop would leave them.
        self._max_pods_per_pass = max_pods_per_pass
        # Coarser pod buckets bound the number of distinct compiled scan
        # shapes (each new padded shape is an XLA compile).
        self._pod_bucket_min = pod_bucket_min
        # Direct-factory mode (library use) bypasses profile compilation.
        self._plugins_factory = plugins_factory
        self._featurizer_override = featurizer
        self._initial_config = copy.deepcopy(config) or {}
        self._config: JSON = {}
        self._profiles: dict[str, CompiledProfile] = {}
        from ksim_tpu.state.priorities import build_priority_resolver

        self._priority_of = build_priority_resolver(())
        # Featurizers persist per profile across passes: they carry the
        # incremental bound-pod aggregates (state/boundagg.py) keyed to
        # an evolving cluster; a config change drops them (re-compile =
        # the reference's scheduler restart).
        self._featurizers: dict[str, Featurizer] = {}
        # The constructor config is operator-owned (code/CLI), so plugin
        # imports are trusted here, like the reference's boot-time wasm
        # registration from the mounted scheduler.yaml.
        self.apply_scheduler_config(copy.deepcopy(self._initial_config), trusted=True)
        self._config_path = config_path
        self._own_rvs: set[str] = set()
        self._own_rvs_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Unschedulable-pod backoff (the upstream scheduling queue's
        # backoff/unschedulable pools, measured in scheduling passes
        # instead of wall-clock): an unschedulable pod skips
        # min(2^(attempts-1), MAX) passes; cluster events that could make
        # it schedulable flush the backoff (QueueingHint analogue).
        self._backoff: dict[str, tuple[int, int]] = {}  # key -> (attempts, retry_at)
        self._backoff_lock = threading.Lock()
        # Pods parked by a Permit plugin's Wait status (the upstream
        # framework's waitingPodsMap): key -> _WaitingPod.  While waiting,
        # a pod is neither pending nor bound; featurization charges its
        # requests to the selected node (the upstream assumed-pod cache).
        self._waiting: dict[str, "_WaitingPod"] = {}
        self._waiting_lock = threading.Lock()
        self._pass_waits = 0
        # Serializes scheduling passes against waiting-pod resolution:
        # allow/reject bind on the CALLER's thread, and doing that while a
        # pass holds a stale pod snapshot could schedule the pod twice.
        # RLock: _expire_waiting runs both inside a pass and standalone.
        self._pass_lock = threading.RLock()
        # Signals the watch loop to run a pass for state changes whose
        # events are rv-suppressed (a rejected waiter returning to the
        # queue).
        self._poke = threading.Event()
        self._pass_count = 0
        self.metrics = Metrics()
        # percentageOfNodesToScore emulation (opt-in replay-fidelity
        # mode, KSIM_PNTS_EMULATION=1): per-profile rotating start index
        # — upstream's sched.nextStartNodeIndex lives on the scheduler,
        # one per profile binary.
        self._pnts_emulation = (
            os.environ.get("KSIM_PNTS_EMULATION", "") == "1"
        )
        self._pnts_start: dict[str, int] = {}

    MAX_BACKOFF_PASSES = 16
    # An event-triggered flush caps the remaining wait instead of zeroing
    # it: upstream cluster events move pods from the indefinite
    # unschedulable pool into the BACKOFF queue — the pod still serves a
    # backoff before retrying (podInitialBackoff).  First-attempt pods
    # retry immediately; repeat offenders keep an attempts-proportional
    # wait, so a churn stream (deletes nearly every step) can't make the
    # whole saturated backlog retry every single pass.
    FLUSH_CAP_PASSES = 4

    def flush_backoff(self) -> None:
        """Accelerate backed-off pods (a node was added/removed or
        capacity freed): remaining wait drops to min(attempts-1, cap)."""
        with self._backoff_lock:
            self._backoff = {
                k: (
                    attempts,
                    min(
                        retry_at,
                        self._pass_count
                        + min(attempts - 1, self.FLUSH_CAP_PASSES),
                    ),
                )
                for k, (attempts, retry_at) in self._backoff.items()
            }

    def _in_backoff(self, pod: JSON) -> bool:
        # _pass_count was already incremented for the pass being built, so
        # a retry_at of P skips passes up to and including P (delay=1 ->
        # exactly one skipped pass).
        key = f"{namespace_of(pod)}/{name_of(pod)}"
        with self._backoff_lock:
            entry = self._backoff.get(key)
            return entry is not None and entry[1] >= self._pass_count

    def _record_attempts(self, placements: dict[str, str | None]) -> None:
        with self._backoff_lock:
            for key, node in placements.items():
                if node is None:
                    # A pod that preemption just nominated expects to
                    # schedule as soon as its victims are gone — upstream
                    # reactivates it on the delete events; never back it
                    # off.
                    ns, _, name = key.partition("/")
                    try:
                        pod = self._store.get("pods", name, ns)
                    except Exception:
                        continue
                    if pod.get("status", {}).get("nominatedNodeName"):
                        self._backoff.pop(key, None)
                        continue
                    attempts = self._backoff.get(key, (0, 0))[0] + 1
                    delay = min(2 ** (attempts - 1), self.MAX_BACKOFF_PASSES)
                    self._backoff[key] = (attempts, self._pass_count + delay)
                else:
                    self._backoff.pop(key, None)

    def _featurizer_for(self, sched_name: str, prof=None) -> Featurizer:
        """The profile's persistent featurizer, created lazily on the
        first pass that needs it — or eagerly by a checkpoint restore
        seeding slot order before any pass has run.  ``prof`` skips the
        profile lookup when the caller already resolved it; an unknown
        profile name raises (the restore path treats that as an
        unusable checkpoint and falls back)."""
        feat = self._featurizers.get(sched_name)
        if feat is None:
            if self._plugins_factory is not None:
                feat = Featurizer(pod_bucket_min=self._pod_bucket_min)
            else:
                if prof is None:
                    prof = self._profiles[sched_name]
                feat = prof.featurizer(pod_bucket_min=self._pod_bucket_min)
            self._featurizers[sched_name] = feat
        return feat

    # -- job-plane checkpoint carries (incremental resume) -------------------

    def checkpoint_carries(self) -> dict:
        """The scheduling-visible carry state a segment checkpoint must
        record for a byte-identical resume (ksim_tpu/jobs/manager.py):
        the pass counter (backoff ``retry_at`` values are measured in
        passes), the unschedulable-backoff map, the pnts rotating start
        indexes, and each persistent featurizer's node-slot ORDER
        (selectHost breaks score ties by lowest slot index, and the
        swap-remove slot order is history-dependent — a fresh
        featurizer's first-seen order would schedule differently).
        ``waiting`` is evidence only: a non-empty Permit waiting map is
        not restorable and makes the caller SKIP the checkpoint."""
        with self._backoff_lock:
            backoff = {k: [a, r] for k, (a, r) in self._backoff.items()}
        with self._waiting_lock:
            waiting = len(self._waiting)
        return {
            "pass_count": self._pass_count,
            "backoff": backoff,
            "pnts_start": dict(self._pnts_start),
            "slots": {
                name: f.slot_names() for name, f in self._featurizers.items()
            },
            "waiting": waiting,
        }

    def restore_carries(self, carry: dict) -> None:
        """Install ``checkpoint_carries`` output on a FRESH service
        (the job worker's restore path, before any pass runs).
        Featurizers for the recorded profiles are created eagerly and
        slot-seeded; the additive bound-pod families start empty and
        rebuild on the first pass — cold but consistent, exactly like
        the replay lower-caches against the restored mutation epoch."""
        self._pass_count = int(carry.get("pass_count", 0))
        with self._backoff_lock:
            self._backoff = {
                str(k): (int(a), int(r))
                for k, (a, r) in (carry.get("backoff") or {}).items()
            }
        self._pnts_start = {
            str(k): int(v) for k, v in (carry.get("pnts_start") or {}).items()
        }
        for name, names in (carry.get("slots") or {}).items():
            self._featurizer_for(name).seed_slots([str(n) for n in names])

    # -- scheduler configuration (reference scheduler.go Service) -----------

    def get_scheduler_config(self) -> JSON:
        """Current KubeSchedulerConfiguration as a typed document.  When
        nothing was ever applied this returns the scheme-defaulted shape
        (kind/apiVersion + the default profile), like the reference's
        DefaultSchedulerConfig (scheduler/config/config.go:19-26) feeding
        the GET handler (handler/schedulerconfig.go:26-40)."""
        cfg = copy.deepcopy(self._config)
        cfg.setdefault("apiVersion", "kubescheduler.config.k8s.io/v1")
        cfg.setdefault("kind", "KubeSchedulerConfiguration")
        if not cfg.get("profiles"):
            # Mirror compile_configuration's falsy test: an explicit empty
            # list also compiles to the default profile, so report it.
            cfg["profiles"] = [
                {"schedulerName": name} for name in sorted(self._profiles)
            ]
        return cfg

    def apply_scheduler_config(self, cfg: JSON, *, trusted: bool = False) -> None:
        """Compile-and-swap — the reference's RestartScheduler with
        rollback (scheduler.go:90-111): a config that fails to compile
        leaves the previous profiles in place and raises."""
        from ksim_tpu.scheduler.extender import ExtenderService

        profiles = compile_configuration(
            cfg,
            registry=self._registry,
            allow_plugin_imports=trusted or self._allow_plugin_imports,
        )
        extenders = ExtenderService((cfg or {}).get("extenders"))
        self._profiles = {p.scheduler_name: p for p in profiles}
        # New kernel set -> fresh featurizers (drops incremental state).
        if getattr(self, "_featurizers", None):
            self._featurizers.clear()
        self._extenders = extenders
        self._config = copy.deepcopy(cfg) or {}
        # Persist the applied config like the reference rewrites the
        # mounted scheduler.yaml (scheduler/config/config.go:33-60
        # UpdateSchedulerConfig) — a restart then boots with it.  An
        # empty config is persisted too (a reset must not resurrect the
        # pre-reset file on restart).  Atomic: dump to a sibling temp
        # file then replace, so a mid-write failure can't truncate the
        # real file.
        if self._config_path:
            try:
                import os
                import yaml

                tmp = f"{self._config_path}.tmp"
                with open(tmp, "w") as f:
                    yaml.safe_dump(self._config, f, sort_keys=False)
                os.replace(tmp, self._config_path)
            except (OSError, yaml.YAMLError):
                logger.exception("failed to write scheduler config")

    @property
    def extender_service(self):
        return self._extenders

    def reset_scheduler_config(self) -> None:
        """Back to the boot-time config (reference di.go initial cfg)."""
        self.apply_scheduler_config(copy.deepcopy(self._initial_config), trusted=True)

    @property
    def _scheduler_names(self) -> tuple[str, ...]:
        if self._plugins_factory is not None:
            return (DEFAULT_SCHEDULER_NAME,)
        return tuple(self._profiles)

    # -- queue --------------------------------------------------------------

    def _is_pending(self, pod: JSON) -> bool:
        if pod.get("spec", {}).get("nodeName"):
            return False
        if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            return False
        # Waiting on Permit: parked, not re-queued (upstream keeps the
        # pod assumed while its waitingPod entry exists).  Unlocked empty
        # check first: this runs once per pod per queue build, and the
        # map is almost always empty.
        if self._waiting:
            with self._waiting_lock:
                if f"{namespace_of(pod)}/{name_of(pod)}" in self._waiting:
                    return False
        # SchedulingGates (upstream PreEnqueue): gated pods never enter
        # the scheduling queue until every gate is removed.
        if pod.get("spec", {}).get("schedulingGates"):
            return False
        name = pod.get("spec", {}).get("schedulerName") or DEFAULT_SCHEDULER_NAME
        return name in self._scheduler_names

    def pending_pods(self) -> list[JSON]:
        """The sorted pending queue (deep copies — callers may mutate).
        Public API (the reference UI lists it); hot loops wanting only
        the size use pending_count()."""
        return copy.deepcopy(self._pending_pods_live())

    def pending_count(self) -> int:
        """Number of pending pods (no copies — the hot-loop counter).
        The store's nodeName partition bounds the walk to the unbound
        side (every bound pod fails _is_pending's first check)."""
        return sum(
            1
            for p in self._store.pods_without_node()
            if self._is_pending(p)
        )

    def _pending_pods_live(self) -> list[JSON]:
        """Internal read-only variant over the store's live dicts."""
        return sorted(
            (p for p in self._store.pods_without_node() if self._is_pending(p)),
            key=lambda p: queue_sort_key(p, self._priority_of),
        )

    # -- one scheduling pass ------------------------------------------------

    def start_profiling(self, log_dir: str) -> None:
        """Start a jax.profiler trace (TensorBoard/XPlane format) with a
        StepTraceAnnotation per scheduling pass — kernel-level device
        timing, the TPU-native layer on top of the metrics counters (the
        reference's observability is the upstream scheduler's Prometheus
        metrics + klog, SURVEY.md section 5)."""
        import jax

        jax.profiler.start_trace(log_dir)
        self._profiling = True

    def stop_profiling(self) -> None:
        if getattr(self, "_profiling", False):
            import jax

            jax.profiler.stop_trace()
            self._profiling = False

    def schedule_pending(self) -> dict[str, str | None]:
        """Schedule every pending pod once (per profile group); returns
        namespace/name -> node name (None = unschedulable this pass).
        Results are recorded on the pods' annotations either way (the
        reference records every attempt; history accumulates)."""
        if getattr(self, "_profiling", False):
            import jax

            with jax.profiler.StepTraceAnnotation(
                "scheduling-pass", step_num=self._pass_count
            ):
                return self._schedule_pending_inner()
        return self._schedule_pending_inner()

    # Machine-checked acquisition order (tools/ksimlint lock-order —
    # docs/lint.md "Lock order"): one pass takes the pass lock
    # OUTERMOST, then everything it needs under it; the backoff lock
    # nests a read-only store lookup; the planes are leaves.
    # ksimlint: lock-order(SchedulerService._pass_lock<SchedulerService._backoff_lock<ClusterStore._lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<SchedulerService._waiting_lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<SchedulerService._own_rvs_lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<ClusterStore._lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<FaultPlane._lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<Metrics._lock)
    # ksimlint: lock-order(SchedulerService._pass_lock<TracePlane._lock)
    def _schedule_pending_inner(self) -> dict[str, str | None]:
        with self._pass_lock:
            # The span covers the pass body only (not the lock wait):
            # queue-contention latency would otherwise masquerade as
            # scheduling latency in the histogram.  A fleet-lane service
            # (engine/fleet.py sets _trace_lane) stamps its lane id so a
            # per-pass fallback pass is attributable to its trajectory.
            tags = {} if self._trace_lane is None else {"lane": self._trace_lane}
            with TRACE.span(
                "service.schedule", pass_num=self._pass_count + 1, **tags
            ):
                return self._schedule_pending_locked()

    def _schedule_pending_locked(self) -> dict[str, str | None]:
        # Fault-plane site: an injected fault aborts the pass BEFORE any
        # bookkeeping mutates (pass counter, placements) — the watch
        # loop's containment (its except around schedule_pending) and
        # the runner's step retry are what a schedule here exercises.
        FAULTS.check("service.schedule")
        nodes = self._store.list("nodes", copy_objs=False)
        namespaces = self._store.list("namespaces", copy_objs=False)
        volume_kw = dict(
            pvs=self._store.list("persistentvolumes", copy_objs=False),
            pvcs=self._store.list("persistentvolumeclaims", copy_objs=False),
            storage_classes=self._store.list("storageclasses", copy_objs=False),
        )
        from ksim_tpu.state.priorities import build_priority_resolver

        self._priority_of = build_priority_resolver(
            self._store.list("priorityclasses", copy_objs=False)
        )
        if not nodes:
            return {}
        self._pass_count += 1
        placements: dict[str, str | None] = {}
        self._expire_waiting()
        # Permit-WAIT placements carry a node name (the assumed node) but
        # nothing bound yet; _finalize_waiting counts the eventual bind.
        self._pass_waits = 0
        for sched_name in self._scheduler_names:
            # Fresh pod snapshot per profile: earlier profiles' bindings
            # must charge their nodes before the next profile evaluates.
            # The store's nodeName partition replaces the O(all pods)
            # walk: queue candidates come from the without-node side
            # (permit-assumed pods gain a nodeName in the wrap and fall
            # out via _is_pending, exactly as they did from the full
            # list), bound pods from the with-node side.
            without = self._assume_waiting(self._store.pods_without_node())
            bound_pods = self._store.pods_with_node()
            assumed = [p for p in without if p.get("spec", {}).get("nodeName")]
            if assumed:
                bound_pods = bound_pods + assumed
            queue = [
                p
                for p in without
                if self._is_pending(p)
                and not self._in_backoff(p)
                and (p.get("spec", {}).get("schedulerName") or DEFAULT_SCHEDULER_NAME)
                == sched_name
            ]
            if not queue:
                continue
            prof = (
                self._profiles.get(sched_name)
                if self._plugins_factory is None
                else None
            )
            # PreEnqueue gates (upstream wrappedplugin.go:376; structural
            # SchedulingGates already filtered in _is_pending): any hook
            # returning a message keeps the pod out of this pass's queue.
            if prof is not None and prof.pre_enqueue_hooks:
                queue = [p for p in queue if self._pre_enqueue_admits(prof, p)]
                if not queue:
                    continue
            # Custom QueueSort replaces PrioritySort's order
            # (wrappedplugin.go:750-765).
            if prof is not None and prof.queue_sort_plugin is not None:
                _qs_name, qs_key = prof.queue_sort_plugin
                queue.sort(key=lambda p: qs_key(p, self._priority_of))
            else:
                queue.sort(key=lambda p: queue_sort_key(p, self._priority_of))
            if self._max_pods_per_pass is not None:
                queue = queue[: self._max_pods_per_pass]
            featurizer = self._featurizer_override
            if featurizer is None:
                featurizer = self._featurizer_for(sched_name, prof)
            if self._plugins_factory is not None:
                factory: PluginsFactory = self._plugins_factory
            else:
                factory = prof.plugins
            if self._extenders:
                # Webhook extenders need per-pod HTTP round-trips between
                # filtering and scoring — exact upstream semantics require
                # pod-at-a-time evaluation (the reference's scheduler is
                # per-pod anyway; extenders are the slow path by design).
                if self._pnts_emulation and not getattr(
                    self, "_pnts_extender_warned", False
                ):
                    # Sampling emulation does not apply on this path (it
                    # lives in the scan program) — say so once instead of
                    # silently scoring every node under the flag.
                    self._pnts_extender_warned = True
                    logger.warning(
                        "KSIM_PNTS_EMULATION=1 is inert for profiles "
                        "with extenders (per-pod evaluation path scores "
                        "all nodes)"
                    )
                self._schedule_queue_with_extenders(
                    queue, featurizer, factory, namespaces, volume_kw, placements,
                    prof=prof,
                )
                continue
            with self.metrics.timer("featurize"):
                feats = featurizer.featurize(
                    nodes,
                    (),
                    queue_pods=queue,
                    bound_pods=bound_pods,
                    namespaces=namespaces,
                    **volume_kw,
                )
            plugins = tuple(factory(feats))
            sampling_k = self._sampling_k_for(prof, len(nodes))
            with self.metrics.timer("engine"):
                eng = Engine(
                    feats, plugins, record=self._record, sampling_k=sampling_k
                )
                if self._shard_mesh is not None:
                    eng.shard(self._shard_mesh)
                res, _ = eng.schedule(
                    pull_state=False,
                    sampling_start=self._pnts_start.get(sched_name, 0),
                )
            if sampling_k is not None and res.sampling_next_start is not None:
                self._pnts_start[sched_name] = res.sampling_next_start
            with self.metrics.timer("bind"):
                self._bind_results(queue, feats, plugins, res, placements, prof=prof)
        # Bound _own_rvs growth for library use (schedule_pending without
        # the watch loop draining events).  The limit scales with the pass
        # size so one large pass never trims its own still-queued events
        # out of the suppression set (that would retrigger endless passes).
        with self._own_rvs_lock:
            limit = max(_OWN_RV_LIMIT, 2 * len(placements))
            if len(self._own_rvs) > limit:
                for rv in sorted(self._own_rvs, key=int)[:-limit]:
                    self._own_rvs.discard(rv)
        self._record_attempts(placements)
        if TRACE.active:
            TRACE.event(
                "service.pass",
                pass_num=self._pass_count,
                attempts=len(placements),
                scheduled=sum(1 for v in placements.values() if v is not None),
                unschedulable=sum(1 for v in placements.values() if v is None),
            )
        self.metrics.inc("scheduling_passes")
        self.metrics.inc("scheduling_attempts", len(placements))
        self.metrics.inc(
            "pods_scheduled",
            sum(1 for v in placements.values() if v is not None) - self._pass_waits,
        )
        self.metrics.inc(
            "pods_unschedulable", sum(1 for v in placements.values() if v is None)
        )
        with self._backoff_lock:
            if len(self._backoff) > 2 * len(placements) + 64:
                alive = {
                    f"{namespace_of(p)}/{name_of(p)}"
                    for p in self._store.list("pods", copy_objs=False)
                }
                self._backoff = {
                    k: v for k, v in self._backoff.items() if k in alive
                }
        return placements

    def _schedule_queue_with_extenders(
        self, queue, featurizer, factory, namespaces, volume_kw, placements,
        prof=None,
    ) -> None:
        """Per-pod cycle with extender webhooks (upstream
        findNodesThatPassExtenders + prioritizeNodes extender scores):
        engine filters/scores the pod batch-style against all nodes, then
        each configured extender filters the feasible set and adds
        prioritize scores before selectHost."""
        import numpy as np

        for pod in queue:
            nodes = self._store.list("nodes", copy_objs=False)
            pods = self._assume_waiting(self._store.list("pods", copy_objs=False))
            with self.metrics.timer("featurize"):
                feats = featurizer.featurize(
                    nodes, pods, queue_pods=[pod], namespaces=namespaces, **volume_kw
                )
            plugins = tuple(factory(feats))
            with self.metrics.timer("engine"):
                eng = Engine(feats, plugins, record="full")
                res = eng.evaluate_batch()
            n_valid = feats.nodes.count
            ok = np.asarray(res.reason_bits[0] == 0).all(axis=0)[:n_valid]
            feasible = [feats.nodes.names[i] for i in range(n_valid) if ok[i]]
            node_objs = {name_of(n): n for n in nodes}
            failed = False
            for idx, ext in enumerate(self._extenders.extenders):
                if not feasible:
                    break
                if not ext.filter_verb:
                    continue
                # managedResources gate (extender.go:99-112): extenders
                # managing specific resources only see pods requesting them.
                if not ext.is_interested(pod):
                    continue
                args = {"pod": pod}
                if ext.node_cache_capable:
                    args["nodenames"] = list(feasible)
                else:
                    args["nodes"] = {"items": [node_objs[n] for n in feasible]}
                try:
                    result = self._extenders.filter(idx, args)
                except Exception:
                    logger.exception("extender %s filter failed", ext.name)
                    if ext.ignorable:
                        continue
                    failed = True
                    break
                if result.get("error"):
                    if ext.ignorable:
                        continue
                    failed = True
                    break
                if result.get("nodenames") is not None:
                    keep = set(result["nodenames"])
                    feasible = [n for n in feasible if n in keep]
                elif result.get("nodes") is not None:
                    keep = {
                        name_of(item) for item in result["nodes"].get("items") or []
                    }
                    feasible = [n for n in feasible if n in keep]
            selected = None
            if feasible and not failed:
                feasible_set = set(feasible)
                totals = {
                    feats.nodes.names[i]: int(res.total[0, i])
                    for i in range(n_valid)
                    if feats.nodes.names[i] in feasible_set
                }
                for idx, ext in enumerate(self._extenders.extenders):
                    if not ext.prioritize_verb:
                        continue
                    if not ext.is_interested(pod):
                        continue
                    args = {"pod": pod}
                    if ext.node_cache_capable:
                        args["nodenames"] = list(feasible)
                    else:
                        args["nodes"] = {"items": [node_objs[n] for n in feasible]}
                    try:
                        for hp in self._extenders.prioritize(idx, args):
                            host = hp.get("host")
                            if host in totals:
                                totals[host] += int(hp.get("score") or 0)
                    except Exception:
                        logger.exception("extender %s prioritize failed", ext.name)
                # selectHost: max score, lowest node index on ties.
                order = {n: i for i, n in enumerate(feats.nodes.names)}
                selected = max(feasible, key=lambda n: (totals[n], -order[n]))
            # PostFilter still runs when nothing fit (the batch path's
            # preemption applies identically; extenders may further have a
            # preemptVerb — the proxy route records it when an external
            # scheduler drives it).
            nominated, victims, postfilter = None, [], None
            # An aborted cycle (non-ignorable extender error) never runs
            # PostFilter — upstream gives up on the pod for this pass.
            if selected is None and not failed:
                nominated, victims, postfilter = self._run_post_filter(
                    pod, feats, plugins, res, 0, prof=prof
                )
            # Reserve -> Permit -> PreBind/Bind on this path too
            # (upstream's cycle is identical with or without extenders).
            reserve_extra: dict[str, str] = {}
            reserve_failed = False
            if selected is not None:
                reserve_extra, reserve_failed = self._run_reserve(
                    plugins, pod, selected
                )
                if reserve_failed:
                    self._run_unreserve(plugins, pod, selected)
            permit_maps = None
            permit_verdict = SUCCESS
            wait_deadlines: dict[str, float] = {}
            if selected is not None and not reserve_failed:
                permit_verdict, permit_maps, wait_deadlines = self._run_permit(
                    plugins, pod, selected
                )
                if permit_verdict == REJECT:
                    self._run_unreserve(plugins, pod, selected)
            prebind_extra: dict[str, str] = {}
            bind_map = None
            bind_ok = not reserve_failed
            if selected is not None and not reserve_failed and permit_verdict == SUCCESS:
                prebind_extra, prebind_failed = self._run_pre_bind(
                    plugins, pod, selected
                )
                if prebind_failed:
                    bind_ok = False
                    bind_map = {}
                else:
                    bind_map, bind_ok = self._run_bind(
                        plugins, pod, selected, prof=prof
                    )
                if not bind_ok:
                    self._run_unreserve(plugins, pod, selected)
            anno = render_pod_results(
                feats,
                plugins,
                res,
                0,
                postfilter=postfilter,
                permit=permit_maps,
                bound=permit_verdict != REJECT and bind_ok,
                reserve_extra=reserve_extra,
                prebind_extra=prebind_extra,
                bind_map=bind_map,
                visited=None if res.visited is None else res.visited[0],
            )
            anno.update(self._extenders.store.get_stored_result(pod))
            selected_settle = None if reserve_failed else selected
            selected, parked = self._settle_permit(
                pod, selected_settle, permit_verdict, wait_deadlines, anno,
                placements, plugins=plugins, prof=prof,
            )
            if parked:
                self._extenders.store.delete_data(pod)
                continue
            if not bind_ok:
                selected = None

            def mutate(obj: JSON) -> None:
                annos = obj.setdefault("metadata", {}).setdefault("annotations", {})
                apply_results_to_pod(annos, anno)
                if selected:
                    obj.setdefault("spec", {})["nodeName"] = selected
                    obj.setdefault("status", {})["phase"] = "Running"
                    obj.get("status", {}).pop("nominatedNodeName", None)
                elif nominated:
                    obj.setdefault("status", {})["nominatedNodeName"] = nominated

            try:
                updated = self._store.patch(
                    "pods", name_of(pod), namespace_of(pod), mutate
                )
            except NotFoundError:
                # Deleted mid-cycle: fail just this pod (see _bind_results).
                logger.info(
                    "pod %s/%s deleted mid-cycle; skipping its bind",
                    namespace_of(pod), name_of(pod),
                )
                self._extenders.store.delete_data(pod)
                continue
            self._extenders.store.delete_data(pod)
            with self._own_rvs_lock:
                self._own_rvs.add(updated["metadata"]["resourceVersion"])
            if selected is not None:
                self._run_post_bind(plugins, updated, selected)
            for v in victims:
                self._evict_victim(v)
            placements[f"{namespace_of(pod)}/{name_of(pod)}"] = selected

    # Upstream sampling constants (schedule_one.go).
    _MIN_FEASIBLE_NODES_TO_FIND = 100
    _MIN_FEASIBLE_PERCENTAGE = 5

    def _sampling_k_for(self, prof, n_nodes: int) -> int | None:
        """numFeasibleNodesToFind (schedule_one.go): None = score all
        nodes (emulation off, small cluster, or percentage resolves to
        everything).  A per-profile percentageOfNodesToScore overrides
        the global field; 0/unset means the adaptive formula
        50 - n/125, floored at 5%."""
        if not self._pnts_emulation:
            return None
        if n_nodes < self._MIN_FEASIBLE_NODES_TO_FIND:
            return None
        pct = None
        if prof is not None and prof.percentage_of_nodes_to_score is not None:
            pct = prof.percentage_of_nodes_to_score
        if pct is None:
            v = (self._config or {}).get("percentageOfNodesToScore")
            pct = v if isinstance(v, int) else 0
        if pct == 0:
            pct = max(50 - n_nodes // 125, self._MIN_FEASIBLE_PERCENTAGE)
        if pct >= 100:
            return None
        k = max(n_nodes * pct // 100, self._MIN_FEASIBLE_NODES_TO_FIND)
        return None if k >= n_nodes else k

    def add_eviction_listener(self, fn) -> None:
        """Register a (namespace, name) callback fired right after each
        preemption victim's SUCCESSFUL store delete (see __init__ note;
        the victim is already gone from the store when it fires)."""
        self._eviction_listeners.append(fn)

    def _evict_victim(self, v: JSON, *, listener_sink=None) -> None:
        """Preemption eviction (the debuggable scheduler deletes victims
        via the apiserver; KWOK terminates immediately).  Listeners run
        only AFTER the store delete succeeded — a mark for a delete that
        never happened would leak and misclassify a LATER plain delete
        of a same-named pod as an eviction (the write-back's DELETED
        handler rechecks once to absorb the mark-after-event race).

        ``listener_sink`` defers the listener callbacks: the successful
        eviction appends ``(namespace, name)`` there instead of firing,
        and the caller replays the sink through ``_notify_evictions``
        once its batch is durable — the device replay's atomic segment
        reconcile stages evictions inside a store transaction and must
        not announce one that could still roll back."""
        try:
            self._store.delete("pods", name_of(v), namespace_of(v))
        except Exception:
            logger.exception("failed to evict victim %s", name_of(v))
            return
        ev = (namespace_of(v) or "default", name_of(v))
        if listener_sink is not None:
            listener_sink.append(ev)
            return
        self._notify_evictions([ev])

    def _notify_evictions(self, evictions) -> None:
        """Fire eviction listeners for ``(namespace, name)`` tuples in
        order (each listener isolated — one failing must not starve the
        rest)."""
        for ns, nm in evictions:
            for fn in self._eviction_listeners:
                try:
                    fn(ns, nm)
                except Exception:
                    logger.exception("eviction listener failed")

    def _bind_results(self, queue, feats, plugins, res, placements, prof=None) -> None:
        render_ctx = RenderCtx(feats, plugins) if self._record == "full" else None
        for j, pod in enumerate(queue):
            sel = int(res.selected[j])
            node_name = feats.nodes.names[sel] if sel >= 0 else None
            nominated, victims, postfilter = None, [], None
            if node_name is None:
                nominated, victims, postfilter = self._run_post_filter(
                    pod, feats, plugins, res, j, prof=prof
                )
            # Reserve runs first on a selected node (upstream cycle
            # order: Reserve -> Permit -> WaitOnPermit -> PreBind ->
            # Bind); its failure unreserves and fails the cycle.
            reserve_extra: dict[str, str] = {}
            reserve_failed = False
            if node_name is not None:
                reserve_extra, reserve_failed = self._run_reserve(
                    plugins, pod, node_name
                )
                if reserve_failed:
                    self._run_unreserve(plugins, pod, node_name)
            # Permit runs after selection (upstream RunPermitPlugins is
            # post-Reserve, wrappedplugin.go:582-611).
            permit_maps = None
            permit_verdict = SUCCESS
            wait_deadlines: dict[str, float] = {}
            if node_name is not None and not reserve_failed:
                permit_verdict, permit_maps, wait_deadlines = self._run_permit(
                    plugins, pod, node_name
                )
                if permit_verdict == REJECT:
                    self._run_unreserve(plugins, pod, node_name)
            # PreBind/Bind chains (upstream: post-WaitOnPermit; for
            # permit-parked pods they run at allow time instead,
            # _finalize_waiting).
            prebind_extra: dict[str, str] = {}
            bind_map = None
            bind_ok = not reserve_failed
            if node_name is not None and not reserve_failed and permit_verdict == SUCCESS:
                prebind_extra, prebind_failed = self._run_pre_bind(
                    plugins, pod, node_name
                )
                if prebind_failed:
                    bind_ok = False
                    bind_map = {}
                else:
                    bind_map, bind_ok = self._run_bind(
                        plugins, pod, node_name, prof=prof
                    )
                if not bind_ok:
                    self._run_unreserve(plugins, pod, node_name)
            anno = (
                render_pod_results(
                    feats,
                    plugins,
                    res,
                    j,
                    postfilter=postfilter,
                    permit=permit_maps,
                    bound=permit_verdict != REJECT and bind_ok,
                    reserve_extra=reserve_extra,
                    prebind_extra=prebind_extra,
                    bind_map=bind_map,
                    ctx=render_ctx,
                    visited=None if res.visited is None else res.visited[j],
                )
                if self._record == "full"
                else {}
            )
            node_name_settle = None if reserve_failed else node_name
            node_name, parked = self._settle_permit(
                pod, node_name_settle, permit_verdict, wait_deadlines, anno,
                placements, plugins=plugins, prof=prof,
            )
            if parked:
                continue
            if not bind_ok:
                # A Reserve/PreBind/Bind failure fails the cycle: the pod
                # stays pending (upstream unreserves and requeues), the
                # attempt is recorded.
                node_name = None

            def rebuild(obj: JSON) -> JSON:
                # Shallow re-wrap (store.rewrap contract): share the
                # unchanged substructures, never mutate the old object —
                # deep-copying megabytes of accumulated result-history
                # per attempt dominated the record="full" product path.
                new = dict(obj)
                md = dict(obj.get("metadata") or {})
                annos = dict(md.get("annotations") or {})
                if anno:
                    apply_results_to_pod(annos, anno)
                md["annotations"] = annos
                new["metadata"] = md
                spec = dict(obj.get("spec") or {})
                status = dict(obj.get("status") or {})
                if node_name:
                    spec["nodeName"] = node_name
                    status["phase"] = "Running"
                    # The apiserver clears any earlier nomination on bind.
                    status.pop("nominatedNodeName", None)
                elif nominated:
                    status["nominatedNodeName"] = nominated
                new["spec"] = spec
                new["status"] = status
                return new

            try:
                updated = self._store.rewrap(
                    "pods", name_of(pod), namespace_of(pod), rebuild
                )
            except NotFoundError:
                # The pod was deleted while this pass ran (a reset or an
                # external delete during a long compile): upstream's Bind
                # fails just THAT pod; the rest of the batch still binds.
                logger.info(
                    "pod %s/%s deleted mid-pass; skipping its bind",
                    namespace_of(pod), name_of(pod),
                )
                continue
            with self._own_rvs_lock:
                self._own_rvs.add(updated["metadata"]["resourceVersion"])
            if node_name is not None:
                self._run_post_bind(plugins, updated, node_name)
            # Evict the victims (the debuggable scheduler deletes them via
            # the apiserver; KWOK terminates immediately).  The DELETED
            # events trigger the next pass, which schedules the preemptor.
            for v in victims:
                self._evict_victim(v)
            placements[f"{namespace_of(pod)}/{name_of(pod)}"] = node_name

    # -- host extension points (PreEnqueue/PostFilter/PreBind/Bind/PostBind) -

    def _pre_enqueue_admits(self, prof, pod: JSON) -> bool:
        """All PreEnqueue hooks must return None (upstream: any
        non-success status keeps the pod out of the queue; an erroring
        gate blocks, like an upstream Error status)."""
        for name, hook in prof.pre_enqueue_hooks:
            try:
                msg = hook(pod)
            except Exception as e:
                logger.exception("pre-enqueue hook %s failed", name)
                msg = f"pre-enqueue error: {e}"
            if msg is not None:
                return False
        return True

    @staticmethod
    def _host_hooks(sp, hook_attr: str):
        """(hook, before, after) for one host extension point: the
        plugin's own ``hook_attr`` method plus the extender pair named
        ``before_<hook_attr>`` / ``after_<hook_attr>`` (PluginExtender
        host fields — the reference's Before/After extender interfaces,
        wrappedplugin.go:47-171).  All None when nothing is implemented."""
        hook = getattr(sp.plugin, hook_attr, None)
        ext = getattr(sp, "extender", None)
        before = getattr(ext, f"before_{hook_attr}", None) if ext else None
        after = getattr(ext, f"after_{hook_attr}", None) if ext else None
        return hook, before, after

    @staticmethod
    def _call_hook(point: str, name: str, fn, *args):
        """Run one hook under the shared error contract: an exception is
        logged and maps to the point's error status string (upstream
        converts plugin panics to Error statuses).  Returns
        (value, error_message) — exactly one is meaningful."""
        try:
            return fn(*args), None
        except Exception as e:
            logger.exception("%s hook of plugin %s failed", point, name)
            return None, f"{point} error: {e}"

    def _run_post_filter(self, pod, feats, plugins, res, j, prof=None):
        """The PostFilter chain: DefaultPreemption (structural) first in
        its default-config position, then out-of-tree ``post_filter``
        hooks in plugin order until one nominates a node — upstream
        RunPostFilterPlugins stops at the first success
        (wrappedplugin.go:550-577 wraps each).  Returns
        (nominated, victims, postfilter_annotation_map)."""
        nominated, victims, post = None, [], None
        default_on = self._preemption and (
            prof is None or "DefaultPreemption" not in prof.postfilter_disabled
        )
        if default_on:
            nominated, victims, post = self._attempt_preemption(
                pod, feats, plugins, res, j
            )
        if nominated is not None:
            return nominated, victims, post
        # Built lazily: with no custom PostFilter hooks registered (the
        # common case — 42829 unschedulable attempts per 50k churn
        # replay), materializing the full node-name list per attempt was
        # pure overhead (~3.5 s of the replay).
        failed_nodes: list[str] | None = None
        ran_custom = False
        for sp in plugins:
            if not getattr(sp, "postfilter_enabled", False):
                continue
            hook, before, after = self._host_hooks(sp, "post_filter")
            if hook is None and before is None and after is None:
                # plugins_factory-built sets carry default-True flags;
                # only a real hook makes this a PostFilter plugin.
                continue
            ran_custom = True
            if failed_nodes is None:
                failed_nodes = [
                    feats.nodes.names[i] for i in range(feats.nodes.count)
                ]
            name = sp.plugin.name
            msg = None
            nom = None
            if before is not None:
                msg, err = self._call_hook("postfilter extender", name, before, pod)
                msg = err if err is not None else msg
            if msg is None:
                if hook is not None:
                    nom, _err = self._call_hook(
                        "postfilter", name, hook, pod, list(failed_nodes)
                    )
                if after is not None:
                    pair, err = self._call_hook(
                        "postfilter extender", name, after, pod, nom, msg
                    )
                    if err is not None or not (
                        isinstance(pair, tuple) and len(pair) == 2
                    ):
                        nom, msg = None, err or (
                            f"postfilter extender {name} returned {pair!r}"
                        )
                    else:
                        nom, msg = pair
            if nom is not None and nom in set(failed_nodes):
                from ksim_tpu.scheduler.preemption import NOMINATED_MESSAGE

                if post is None:
                    post = {n: {} for n in failed_nodes}
                post[nom] = {name: NOMINATED_MESSAGE}
                return nom, victims, post
        if post is None and ran_custom:
            post = {n: {} for n in failed_nodes}
        return nominated, victims, post

    def _run_status_chain(
        self,
        plugins,
        pod: JSON,
        node_name: str,
        *,
        hook_attr: str,
        point: str,
        enabled_attr: str,
    ):
        """Shared shape of the Reserve and PreBind chains (upstream runs
        both in order and stops at the first failure, which fails the
        cycle): before may short-circuit with a message, the original
        hook returns a message on failure, after may replace it.
        Returns ({plugin: success-or-message}, failed)."""
        from ksim_tpu.engine.annotations import SUCCESS_MESSAGE

        extra: dict[str, str] = {}
        for sp in plugins:
            hook, before, after = self._host_hooks(sp, hook_attr)
            if hook is None and before is None and after is None:
                continue
            if not getattr(sp, enabled_attr, True):
                continue
            name = sp.plugin.name
            msg = None
            if before is not None:
                msg, err = self._call_hook(f"{point} extender", name, before, pod, node_name)
                msg = err if err is not None else msg
            if msg is None and hook is not None:
                msg, err = self._call_hook(f"{point} plugin", name, hook, pod, node_name)
                msg = err if err is not None else msg
            if after is not None:
                out, err = self._call_hook(
                    f"{point} extender", name, after, pod, node_name, msg
                )
                msg = err if err is not None else out
            extra[name] = SUCCESS_MESSAGE if msg is None else str(msg)
            if msg is not None:
                return extra, True
        return extra, False

    def _run_notify_chain(
        self,
        plugins,
        pod: JSON,
        node_name: str,
        *,
        hook_attr: str,
        point: str,
        enabled_attr: str,
        enabled_default: bool,
        reverse: bool = False,
    ) -> None:
        """Shared shape of the void notification chains (PostBind, and
        Unreserve which runs in REVERSE order — upstream
        wrappedplugin.go:650-668, :728-746): a non-None Before skips the
        original hook; all errors are logged, never propagated."""
        ordered = reversed(list(plugins)) if reverse else plugins
        for sp in ordered:
            if not getattr(sp, enabled_attr, enabled_default):
                continue
            hook, before, after = self._host_hooks(sp, hook_attr)
            if hook is None and before is None and after is None:
                continue
            name = sp.plugin.name
            if before is not None:
                msg, err = self._call_hook(
                    f"{point} extender", name, before, pod, node_name
                )
                if msg is not None or err is not None:
                    logger.warning(
                        "%s extender %s blocked the original hook", point, name
                    )
                    continue
            if hook is not None:
                self._call_hook(f"{point} plugin", name, hook, pod, node_name)
            if after is not None:
                self._call_hook(f"{point} extender", name, after, pod, node_name)

    def _run_reserve(self, plugins, pod: JSON, node_name: str):
        """The Reserve chain (upstream RunReservePlugins; the wrapper
        also records the selected node there, wrappedplugin.go:616-648 —
        this codebase does that via the selected-node annotation)."""
        return self._run_status_chain(
            plugins, pod, node_name,
            hook_attr="reserve", point="reserve", enabled_attr="reserve_enabled",
        )

    def _run_unreserve(self, plugins, pod: JSON, node_name: str) -> None:
        """Unreserve on every post-Reserve failure (wrappedplugin.go:650-668)."""
        self._run_notify_chain(
            plugins, pod, node_name,
            hook_attr="unreserve", point="unreserve",
            enabled_attr="reserve_enabled", enabled_default=True, reverse=True,
        )

    def _run_pre_bind(self, plugins, pod: JSON, node_name: str):
        """Out-of-tree PreBind hooks (upstream RunPreBindPlugins stops at
        the first failure; a failure fails the scheduling cycle)."""
        return self._run_status_chain(
            plugins, pod, node_name,
            hook_attr="pre_bind", point="prebind", enabled_attr="prebind_enabled",
        )

    def _run_bind(self, plugins, pod: JSON, node_name: str, prof=None):
        """The Bind chain (upstream RunBindPlugins: plugins in order; Skip
        falls through, the first non-Skip handles the bind;
        wrappedplugin.go:699-726 records per-binder results).  A custom
        ``bind(pod, node_name)`` returns None to skip, True when it
        accepts the bind (the store write — the simulated apiserver — is
        still the service's, exactly as the reference's wrapped binder
        ultimately binds through the simulator's apiserver), or a message
        string on failure.  Returns ({binder: status}, ok)."""
        from ksim_tpu.engine.annotations import SUCCESS_MESSAGE

        for sp in plugins:
            if not getattr(sp, "bind_enabled", False):
                continue
            hook, before, after = self._host_hooks(sp, "bind")
            name = sp.plugin.name
            outcome = None
            if before is not None:
                outcome, err = self._call_hook("bind extender", name, before, pod, node_name)
                outcome = err if err is not None else outcome
            if outcome is None and hook is not None:
                outcome, err = self._call_hook("bind plugin", name, hook, pod, node_name)
                outcome = err if err is not None else outcome
            if after is not None:
                out, err = self._call_hook(
                    "bind extender", name, after, pod, node_name, outcome
                )
                outcome = err if err is not None else out
            if outcome is None:
                continue  # Skip: next bind plugin
            if outcome is True:
                return {name: SUCCESS_MESSAGE}, True
            return {name: str(outcome)}, False
        if prof is not None and "DefaultBinder" in prof.bind_disabled:
            # No binder handled the pod (upstream: "no Bind plugin" error).
            return {}, False
        return {"DefaultBinder": SUCCESS_MESSAGE}, True

    def _run_post_bind(self, plugins, pod: JSON, node_name: str) -> None:
        """PostBind notifications after a successful bind (upstream
        RunPostBindPlugins is void; wrappedplugin.go:728-746 — a
        non-success BeforePostBind skips the original hook)."""
        self._run_notify_chain(
            plugins, pod, node_name,
            hook_attr="post_bind", point="postbind",
            enabled_attr="postbind_enabled", enabled_default=False,
        )

    # -- Permit (upstream RunPermitPlugins + waitingPodsMap) ----------------

    def _run_permit(
        self, plugins, pod: JSON, node_name: str
    ) -> tuple[str, tuple[dict, dict], dict[str, float]]:
        """Run every permit-capable plugin for the selected (pod, node).

        Returns (verdict, ({plugin: status_msg}, {plugin: timeout_str}),
        {plugin: monotonic_deadline}).  Verdict: REJECT if any plugin
        rejected/errored, else WAIT if any asked to wait, else SUCCESS —
        upstream RunPermitPlugins merges statuses the same way."""
        import time as _time

        statuses: dict[str, str] = {}
        timeouts: dict[str, str] = {}
        deadlines: dict[str, float] = {}
        verdict = SUCCESS
        for sp in plugins:
            hook, before, after = self._host_hooks(sp, "permit")
            if (hook is None and before is None and after is None) or not getattr(
                sp, "permit_enabled", True
            ):
                continue
            name = sp.plugin.name
            result = None
            if before is not None:
                # A non-success BeforePermit skips the original hook and
                # becomes the point's status (extender iface semantics,
                # wrappedplugin.go:47-171).
                msg, err = self._call_hook("permit extender", name, before, pod, node_name)
                msg = err if err is not None else msg
                if msg is not None:
                    result = PermitResult.reject(str(msg))
            if result is None:
                if hook is not None:
                    # An erroring plugin rejects (upstream Error status).
                    result, err = self._call_hook("permit plugin", name, hook, pod, node_name)
                    if err is not None:
                        result = PermitResult.reject(err)
                else:
                    # Extender-only entry: a nil original permit succeeds
                    # (the wrapped plugin returns success when the
                    # original is absent).
                    result = PermitResult.allow()
                if after is not None:
                    result, err = self._call_hook(
                        "permit extender", name, after, pod, node_name, result
                    )
                    if err is not None:
                        result = PermitResult.reject(err)
            if not isinstance(result, PermitResult):
                result = PermitResult.reject(f"permit plugin {name} returned {result!r}")
            # Recorded message: success/wait keywords, otherwise the
            # status message (wrappedplugin.go:596-602).
            if result.status == SUCCESS:
                statuses[name] = SUCCESS
                timeouts[name] = go_duration_str(0)
            elif result.status == WAIT:
                # Clamp at the RUN site like upstream RunPermitPlugins
                # (maxTimeout 15 min) — plugins constructing PermitResult
                # directly must not park pods beyond it.
                from ksim_tpu.scheduler.permit import MAX_WAIT_SECONDS

                timeout_s = min(result.timeout_seconds, MAX_WAIT_SECONDS)
                statuses[name] = WAIT
                timeouts[name] = go_duration_str(timeout_s)
                deadlines[name] = _time.monotonic() + timeout_s
                if verdict == SUCCESS:
                    verdict = WAIT
            else:
                statuses[name] = result.message or "rejected by permit plugin"
                timeouts[name] = go_duration_str(0)
                verdict = REJECT
                # Upstream RunPermitPlugins returns on the first non-wait
                # failure — later plugins never run or record.
                break
        return verdict, (statuses, timeouts), deadlines

    def _settle_permit(
        self,
        pod: JSON,
        node_name: str | None,
        verdict: str,
        deadlines: dict[str, float],
        anno: dict[str, str],
        placements: dict,
        plugins: Sequence[ScoredPlugin] = (),
        prof=None,
    ) -> tuple[str | None, bool]:
        """Resolve a permit verdict for a selected pod: WAIT parks it
        (returns (None, True) — caller skips the bind), REJECT clears the
        selection (upstream Unreserve, no PostFilter), SUCCESS binds.
        ``plugins``/``prof`` ride into the parked entry so the
        PreBind/Bind/PostBind chains can run at allow time."""
        if node_name is not None and verdict == WAIT:
            self._park_waiting(
                pod, node_name, deadlines, anno, placements,
                plugins=plugins, prof=prof,
            )
            return None, True
        if node_name is not None and verdict == REJECT:
            return None, False
        return node_name, False

    def _park_waiting(
        self,
        pod: JSON,
        node_name: str,
        deadlines: dict[str, float],
        anno: dict[str, str],
        placements: dict,
        plugins: Sequence[ScoredPlugin] = (),
        prof=None,
    ) -> None:
        """Park a Permit-WAIT pod: no bind, no pod write yet; the waiting
        entry keeps it out of the queue and charges its node in
        featurization until allow/reject/timeout resolves it."""
        key = f"{namespace_of(pod)}/{name_of(pod)}"
        with self._waiting_lock:
            self._waiting[key] = _WaitingPod(
                name=name_of(pod),
                namespace=namespace_of(pod),
                node_name=node_name,
                pending=deadlines,
                anno=anno,
                plugins=tuple(plugins),
                prof=prof,
            )
        placements[key] = node_name
        self._pass_waits += 1
        self.metrics.inc("pods_waiting_on_permit")

    def _assume_waiting(self, pods: list[JSON]) -> list[JSON]:
        """Charge permit-waiting pods to their selected nodes for
        featurization (the upstream assumed-pod cache: a waiting pod's
        resources are visible to every later scheduling decision)."""
        with self._waiting_lock:
            if not self._waiting:
                return pods
            waiting = dict(self._waiting)
        out = []
        for p in pods:
            wp = waiting.get(f"{namespace_of(p)}/{name_of(p)}")
            if wp is None:
                out.append(p)
            else:
                out.append(
                    dict(p, spec=dict(p.get("spec") or {}, nodeName=wp.node_name))
                )
        return out

    def get_waiting_pods(self) -> list[JSON]:
        """Snapshot of permit-waiting pods (upstream Handle.IterateOverWaitingPods)."""
        with self._waiting_lock:
            return [
                {
                    "name": wp.name,
                    "namespace": wp.namespace,
                    "nodeName": wp.node_name,
                    "pendingPlugins": sorted(wp.pending),
                }
                for wp in self._waiting.values()
            ]

    def allow_waiting_pod(
        self, name: str, namespace: str = "default", plugin: str | None = None
    ) -> bool:
        """Allow a waiting pod for ``plugin`` (or all); binds when no
        pending plugin remains (upstream WaitingPod.Allow).  Serialized
        against scheduling passes (_pass_lock): binding mid-pass could
        let the pass's stale snapshot schedule the pod a second time."""
        key = f"{namespace}/{name}"
        with self._pass_lock:
            with self._waiting_lock:
                wp = self._waiting.get(key)
                if wp is None:
                    return False
                if plugin is None:
                    wp.pending.clear()
                else:
                    wp.pending.pop(plugin, None)
                if wp.pending:
                    return True
                del self._waiting[key]
            self._finalize_waiting(wp, bind=True)
        return True

    def reject_waiting_pod(
        self, name: str, namespace: str = "default", message: str = "rejected"
    ) -> bool:
        """Reject a waiting pod (upstream WaitingPod.Reject): unreserve —
        the pod returns to the pending queue as unschedulable."""
        key = f"{namespace}/{name}"
        with self._pass_lock:
            with self._waiting_lock:
                wp = self._waiting.pop(key, None)
            if wp is None:
                return False
            self._finalize_waiting(wp, bind=False, message=message)
        # The rejection write is rv-suppressed; wake the watch loop so
        # the now-pending pod gets a pass without an unrelated event.
        self._poke.set()
        return True

    def _expire_waiting(self) -> int:
        """Reject waiting pods whose any plugin timer fired (upstream: a
        waiting pod is rejected when one pending plugin's timeout ends).
        Returns the number of pods rejected."""
        import time as _time

        now = _time.monotonic()
        with self._pass_lock:
            expired: list[_WaitingPod] = []
            with self._waiting_lock:
                for key, wp in list(self._waiting.items()):
                    if any(dl <= now for dl in wp.pending.values()):
                        expired.append(wp)
                        del self._waiting[key]
            for wp in expired:
                self._finalize_waiting(
                    wp, bind=False, message="pod rejected: permit wait timed out"
                )
        return len(expired)

    def _finalize_waiting(
        self, wp: _WaitingPod, *, bind: bool, message: str = ""
    ) -> None:
        from ksim_tpu.engine.annotations import (
            BIND_RESULT_KEY,
            PRE_BIND_RESULT_KEY,
            _marshal,
        )
        from ksim_tpu.errors import NotFoundError

        if bind:
            # The assumed node may have been deleted while the pod waited
            # — upstream's Bind would fail and unreserve; do the same.
            try:
                self._store.get("nodes", wp.node_name)
            except NotFoundError:
                bind = False
                message = f"node {wp.node_name} deleted while waiting on permit"

        anno = dict(wp.anno)
        chains_recorded = False
        # The real pod object for the hook chains (both the bind-time
        # PreBind/Bind run and any failure path's Unreserve — hooks key
        # reservations on uid/spec, not just the name).
        pod_obj = {"metadata": {"name": wp.name, "namespace": wp.namespace}}
        try:
            pod_obj = self._store.get("pods", wp.name, wp.namespace)
        except NotFoundError:
            pass
        if bind and wp.plugins:
            # The PreBind/Bind chains run now (upstream: after
            # WaitOnPermit returns success), with the pass's plugin set.
            import json as _json

            prebind_extra, prebind_failed = self._run_pre_bind(
                wp.plugins, pod_obj, wp.node_name
            )
            if prebind_failed:
                bind = False
                message = "prebind failed: " + next(
                    (v for v in reversed(list(prebind_extra.values()))), ""
                )
            bind_map = {} if prebind_failed else None
            if bind:
                bind_map, bind_ok = self._run_bind(
                    wp.plugins, pod_obj, wp.node_name, prof=wp.prof
                )
                if not bind_ok:
                    bind = False
                    message = "bind failed: " + ", ".join(bind_map.values())
            if anno:
                # The chains RAN — their results (including failure
                # messages, wrappedplugin.go AddBindResult) are the
                # record; the rejected-waiter reset below must not wipe
                # them (the inline _bind_results path keeps them too).
                chains_recorded = True
                if prebind_extra and anno.get(PRE_BIND_RESULT_KEY):
                    merged = _json.loads(anno[PRE_BIND_RESULT_KEY])
                    merged.update(prebind_extra)
                    anno[PRE_BIND_RESULT_KEY] = _marshal(merged)
                if bind_map is not None:
                    anno[BIND_RESULT_KEY] = _marshal(bind_map)
        if not bind and anno and not chains_recorded:
            # Bind/PreBind never ran for a rejected waiter.
            anno[BIND_RESULT_KEY] = _marshal({})
            anno[PRE_BIND_RESULT_KEY] = _marshal({})
        if not bind and wp.plugins:
            # Any post-Reserve failure unreserves (upstream Unreserve on
            # permit rejection/timeout and bind failures alike).
            self._run_unreserve(wp.plugins, pod_obj, wp.node_name)

        def rebuild(obj: JSON) -> JSON:
            new = dict(obj)
            md = dict(obj.get("metadata") or {})
            annos = dict(md.get("annotations") or {})
            if anno:
                apply_results_to_pod(annos, anno)
            md["annotations"] = annos
            new["metadata"] = md
            if bind:
                new["spec"] = dict(obj.get("spec") or {}, nodeName=wp.node_name)
                status = dict(obj.get("status") or {}, phase="Running")
                status.pop("nominatedNodeName", None)
                new["status"] = status
            return new

        try:
            updated = self._store.rewrap("pods", wp.name, wp.namespace, rebuild)
        except NotFoundError:
            return  # deleted while waiting
        # Suppress our own write either way: an unsuppressed rejection
        # event would hit _relevant's backoff-clearing branch and erase
        # the backoff recorded below (undamped retry hot loop); the
        # retry pass comes from the explicit _poke instead.
        with self._own_rvs_lock:
            self._own_rvs.add(updated["metadata"]["resourceVersion"])
        if bind:
            self.metrics.inc("pods_scheduled")
            if wp.plugins:
                self._run_post_bind(wp.plugins, updated, wp.node_name)
        else:
            logger.info("permit: pod %s/%s rejected: %s", wp.namespace, wp.name, message)
            key = f"{wp.namespace}/{wp.name}"
            with self._backoff_lock:
                attempts = self._backoff.get(key, (0, 0))[0] + 1
                delay = min(2 ** (attempts - 1), self.MAX_BACKOFF_PASSES)
                self._backoff[key] = (attempts, self._pass_count + delay)
            self.metrics.inc("pods_permit_rejected")

    def _attempt_preemption(self, pod, feats, plugins, res, j):
        """DefaultPreemption for one unschedulable pod (PostFilter).
        Returns (nominated_node, victims, postfilter_annotation_map)."""
        from ksim_tpu.scheduler import preemption as pre

        n_valid = feats.nodes.count
        failed_nodes = feats.nodes.names[:n_valid]
        live_mask = None
        if res.reason_bits is not None:
            mask = self._resolvable_mask(plugins, res.reason_bits[j], n_valid)
            if not mask.any():
                return None, [], pre.render_postfilter_result(failed_nodes, None)
            # feats node order == store list order at featurize time; nodes
            # may have changed since — map the mask by name.
            mask_by_name = {
                feats.nodes.names[i]: bool(mask[i]) for i in range(n_valid)
            }
        # Preemption dry-runs against the LIVE store (upstream uses the
        # live cache in PostFilter) — earlier preemptions in this pass
        # already removed their victims.
        nodes = self._store.list("nodes", copy_objs=False)
        cluster_pods = self._store.list("pods", copy_objs=False)
        namespaces = self._store.list("namespaces", copy_objs=False)
        volumes = dict(
            pvs=self._store.list("persistentvolumes", copy_objs=False),
            pvcs=self._store.list("persistentvolumeclaims", copy_objs=False),
            storage_classes=self._store.list("storageclasses", copy_objs=False),
        )
        if res.reason_bits is not None:
            live_mask = [mask_by_name.get(name_of(n), False) for n in nodes]
        decision = pre.find_preemption(
            pod, nodes, cluster_pods, candidate_mask=live_mask,
            namespaces=namespaces, volumes=volumes,
            priority_of=self._priority_of,
        )
        post = pre.render_postfilter_result(failed_nodes, decision.nominated_node)
        return decision.nominated_node, decision.victims, post

    @staticmethod
    def _resolvable_mask(plugins, bits, n_valid):
        """bool [N]: nodes whose FIRST failing filter plugin (upstream
        Filter chains stop there) reports a preemption-resolvable failure."""
        import numpy as np

        filter_plugins = [sp for sp in plugins if sp.filter_enabled]
        failing = bits != 0  # [F, N]
        fail_any = failing.any(axis=0)
        first = np.argmax(failing, axis=0)
        mask = np.zeros(bits.shape[1], dtype=bool)
        for fi, sp in enumerate(filter_plugins):
            sel = fail_any & (first == fi)
            if not sel.any():
                continue
            rule = getattr(sp.plugin, "failure_unresolvable", None)
            if rule is None:
                continue  # unknown plugin: conservatively unresolvable
            resolvable = {
                int(b): not rule(int(b)) for b in np.unique(bits[fi, sel])
            }
            mask[sel] = [resolvable[int(b)] for b in bits[fi, sel]]
        mask[n_valid:] = False
        return mask

    # -- watch loop ---------------------------------------------------------

    def start(self) -> "SchedulerService":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: "float | None" = 5.0) -> None:
        """Stop the watch loop.  ``timeout=None`` joins indefinitely.  A
        thread that outlives a finite timeout (likely parked in an XLA
        compile; it notices _stop on return) is KEPT on self._thread so a
        later stop() can join it for real — exiting the process with it
        alive risks heap corruption during runtime teardown."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.warning(
                    "scheduler loop still busy after %.0fs; call "
                    "stop(timeout=None) before process exit", timeout or 0
                )
            else:
                self._thread = None

    # Kinds whose changes can make a pending pod schedulable.
    WATCH_KINDS = (
        "pods",
        "nodes",
        "persistentvolumes",
        "persistentvolumeclaims",
        "storageclasses",
    )

    def _relevant(self, ev: WatchEvent) -> bool:
        if ev.kind == "nodes":
            self.flush_backoff()  # topology changed: retry everything
            return True
        if ev.kind in ("persistentvolumes", "persistentvolumeclaims", "storageclasses"):
            # Volume objects gate VolumeBinding/Zone/Limits: retry
            # (upstream requeues on PV/PVC events via QueueingHints).
            self.flush_backoff()
            return True
        if ev.kind != "pods":
            return False
        rv = ev.obj.get("metadata", {}).get("resourceVersion")
        with self._own_rvs_lock:
            if rv in self._own_rvs:
                self._own_rvs.discard(rv)
                return False
        self._flush_extender_results(ev)
        from ksim_tpu.state.cluster import DELETED

        # Drop the pod's backoff either way: a user-driven create/update
        # (self-writes were filtered above) may have made THIS pod
        # schedulable — e.g. editing its requests through the UI — and a
        # deleted pod's entry is garbage (upstream Pod-event QueueingHints
        # move the pod out of the unschedulable pool immediately).
        key = f"{namespace_of(ev.obj)}/{name_of(ev.obj)}"
        with self._backoff_lock:
            self._backoff.pop(key, None)
        if ev.event_type == DELETED:
            # A deleted permit-waiter's entry must die with it — a stale
            # entry would block a re-created same-name pod and write the
            # old pod's annotations onto it at timer expiry.
            with self._waiting_lock:
                self._waiting.pop(key, None)
            self.flush_backoff()  # capacity freed: retry everything
        # A delete frees capacity; an add/update may need scheduling.
        return True

    def _flush_extender_results(self, ev: WatchEvent) -> None:
        """Reflector behavior for proxy-driven EXTERNAL schedulers
        (reference storereflector.go:78-146 merges extender stores onto
        the pod on update events): the in-process path flushes
        synchronously, so anything left here came through the HTTP proxy
        routes."""
        if not self._extenders:
            return
        from ksim_tpu.state.cluster import DELETED

        pod = ev.obj
        if ev.event_type == DELETED:
            self._extenders.store.delete_data(pod)
            return
        anno = self._extenders.store.get_stored_result(pod)
        if not anno:
            return
        from ksim_tpu.errors import ConflictError, NotFoundError
        from ksim_tpu.util import retry_with_exponential_backoff

        try:
            # Conflict-retried like the reference's reflector writes
            # (storereflector.go:124-136 + util/retry.go).  Scoped to
            # ConflictError only: ClusterStore.patch is an atomic RMW so
            # conflicts can't actually occur in-process, and a NotFound
            # (pod deleted meanwhile) must drop straight through instead
            # of stalling the watch loop through the backoff sleeps.
            updated = retry_with_exponential_backoff(
                lambda: self._store.patch(
                    "pods",
                    name_of(pod),
                    namespace_of(pod),
                    lambda obj: obj.setdefault("metadata", {})
                    .setdefault("annotations", {})
                    .update(anno),
                ),
                retriable=(ConflictError,),
            )
        except NotFoundError:
            self._extenders.store.delete_data(pod)
            return
        except Exception:
            logger.exception("failed to flush extender results")
            return
        with self._own_rvs_lock:
            self._own_rvs.add(updated["metadata"]["resourceVersion"])
        self._extenders.store.delete_data(pod)

    def _run(self) -> None:  # ksimlint: thread-role(service-loop)
        stream = self._store.watch(self.WATCH_KINDS)
        try:
            try:
                self.schedule_pending()
            except Exception:  # pragma: no cover - keep the loop alive
                # An initial-pass failure (fault injection found an
                # unprotected call here) must not kill the loop: the
                # periodic idle pass retries pending pods.
                logger.exception("initial scheduling pass failed")
            idle_ticks = 0
            while not self._stop.is_set():
                ev = stream.next(timeout=0.1)
                if ev is None:
                    # Idle tick: permit-wait timers fire here, poked
                    # rejections (whose rv-suppressed MODIFIED events the
                    # loop never sees) get their retry pass, and — because
                    # backoff is measured in PASSES — an idle cluster
                    # still advances backed-off pending pods with a
                    # periodic pass (~1s cadence; an empty eligible queue
                    # makes the pass nearly free), the analogue of
                    # upstream's wall-clock backoff queue draining on
                    # timers rather than on cluster events.
                    poked = self._poke.is_set()
                    if poked:
                        self._poke.clear()
                    idle_ticks += 1
                    periodic = idle_ticks >= 10 and self.pending_count() > 0
                    if self._expire_waiting() or poked or periodic:
                        idle_ticks = 0
                        try:
                            self.schedule_pending()
                        except Exception:  # pragma: no cover
                            logger.exception("scheduling pass failed")
                    continue
                idle_ticks = 0
                if not self._relevant(ev):
                    continue
                # Drain whatever queued behind this event before one pass.
                while True:
                    nxt = stream.next(timeout=0.02)
                    if nxt is None:
                        break
                    self._relevant(nxt)
                try:
                    self.schedule_pending()
                except Exception:  # pragma: no cover - keep the loop alive
                    logger.exception("scheduling pass failed")
        finally:
            stream.close()
