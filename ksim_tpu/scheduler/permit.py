"""Permit extension point (host-side).

The reference wraps Permit plugins and records each plugin's status plus
its wait timeout onto the pod's ``permit-result`` /
``permit-result-timeout`` annotations (reference
simulator/scheduler/plugin/wrappedplugin.go:582-611: success ->
"success", wait -> "wait", otherwise the status message; the timeout is
recorded as Go's ``time.Duration.String()``).  The upstream framework
then parks a Wait pod until every waiting plugin allows it, rejects it
when any plugin rejects, and times each plugin's wait out individually
(k8s.io/kubernetes pkg/scheduler/framework/runtime waitingPodsMap).

Permit plugins here are host-side objects (the decision is per selected
(pod, node) AFTER scoring — nothing to batch), declared by giving a
plugin object a ``permit(pod, node_name) -> PermitResult`` method; the
scheduler service runs them post-selection and owns the waiting-pod map
(allow/reject API + timeout enforcement).
"""

from __future__ import annotations

from dataclasses import dataclass

SUCCESS = "success"
WAIT = "wait"
REJECT = "reject"

# Upstream maxTimeout for Permit waits (framework/runtime: 15 minutes).
MAX_WAIT_SECONDS = 15 * 60


@dataclass(frozen=True)
class PermitResult:
    """One Permit plugin's verdict for (pod, node).

    status: "success" allows immediately; "wait" parks the pod for up to
    ``timeout_seconds``; anything else rejects with ``message`` recorded
    (upstream non-success non-wait statuses: Unschedulable / Error).
    """

    status: str = SUCCESS
    timeout_seconds: float = 0.0
    message: str = ""

    @classmethod
    def allow(cls) -> "PermitResult":
        return cls(SUCCESS)

    @classmethod
    def wait(cls, timeout_seconds: float) -> "PermitResult":
        return cls(WAIT, min(timeout_seconds, MAX_WAIT_SECONDS))

    @classmethod
    def reject(cls, message: str = "") -> "PermitResult":
        return cls(REJECT, 0.0, message)


def go_duration_str(seconds: float) -> str:
    """Go ``time.Duration.String()`` for a non-negative duration —
    byte-compatible with what the reference records in
    ``permit-result-timeout`` (store.go:549-560 ``timeout.String()``)."""
    ns = round(seconds * 1e9)
    if ns == 0:
        return "0s"
    neg = ns < 0
    ns = abs(ns)
    if ns < 1000:
        s = f"{ns}ns"
    elif ns < 1000_000:
        s = _frac(ns, 1000) + "µs"
    elif ns < 1000_000_000:
        s = _frac(ns, 1000_000) + "ms"
    else:
        total_s, frac_ns = divmod(ns, 1000_000_000)
        sec_part = (
            str(total_s % 60)
            if frac_ns == 0
            else _frac((total_s % 60) * 1000_000_000 + frac_ns, 1000_000_000)
        )
        s = sec_part + "s"
        minutes = total_s // 60
        if minutes:
            s = f"{minutes % 60}m" + s
            hours = minutes // 60
            if hours:
                s = f"{hours}h" + s
    return ("-" + s) if neg else s


def _frac(value: int, unit: int) -> str:
    """Integer + trimmed fraction, Go fmtFrac style (e.g. 1500/1000 ->
    "1.5")."""
    whole, rem = divmod(value, unit)
    if rem == 0:
        return str(whole)
    frac = str(rem).rjust(len(str(unit)) - 1, "0").rstrip("0")
    return f"{whole}.{frac}"
