"""Scheduler management layer (reference simulator/scheduler/)."""

from ksim_tpu.scheduler.service import SchedulerService

__all__ = ["SchedulerService"]
