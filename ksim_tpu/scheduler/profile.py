"""KubeSchedulerConfiguration -> compiled kernel profiles.

The reference "compiles" a profile by rewriting the scheduler's
KubeSchedulerConfiguration (wrap every plugin, merge plugin sets, disable
MultiPoint defaults) and restarting the scheduler container (reference
simulator/scheduler/scheduler.go:141-183 ConvertConfigurationForSimulator,
simulator/scheduler/plugin/plugins.go:174-304 ConvertForSimulator/
mergePluginSet/getScorePluginWeight).  The TPU analogue: select + configure
the kernel set for the Engine — "restart" is re-jitting with a new plugin
tuple (Engine construction), with rollback on a config that fails to
compile.

Merge semantics mirror upstream default_plugins.go mergePluginSet:

- start from the default MultiPoint list (order defines filter order and
  therefore early-exit recording order);
- ``disabled`` entries remove by name, ``"*"`` removes all defaults;
- ``enabled`` entries already in the defaults override the weight in
  place; new names append in declaration order;
- the per-extension-point sets (filter/score/...) then enable/disable on
  top, for out-of-tree or re-weighted plugins.

Plugin args honored from pluginConfig (upstream *Args types):
``NodeResourcesFitArgs.scoringStrategy`` (LeastAllocated resources),
``NodeResourcesBalancedAllocationArgs.resources``,
``InterPodAffinityArgs.hardPodAffinityWeight`` (threaded into the
featurizer's inter-pod encoding).

Every upstream default-profile plugin resolves: kernels for the filter/
score families (including the volume family), STRUCTURAL handling in the
service for PrioritySort (queue sort with PriorityClass resolution),
DefaultBinder (bind), DefaultPreemption (postfilter), and SchedulingGates
(queue gate).  Truly unknown names raise; anything enabled without a
kernel would surface through ``CompiledProfile.skipped``.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.state.featurizer import FeaturizedSnapshot, Featurizer
from ksim_tpu.state.interpod import DEFAULT_HARD_POD_AFFINITY_WEIGHT

logger = logging.getLogger(__name__)

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Upstream v1.30 getDefaultPlugins MultiPoint order and weights
# (pkg/scheduler/apis/config/v1/default_plugins.go).
DEFAULT_MULTIPOINT: tuple[tuple[str, int], ...] = (
    ("SchedulingGates", 0),
    ("PrioritySort", 0),
    ("NodeUnschedulable", 0),
    ("NodeName", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("VolumeRestrictions", 0),
    ("NodeVolumeLimits", 0),
    ("VolumeBinding", 0),
    ("VolumeZone", 0),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("DefaultPreemption", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("DefaultBinder", 0),
)

# Plugins realized outside the kernel set.
STRUCTURAL_PLUGINS = frozenset(
    {"SchedulingGates", "PrioritySort", "DefaultPreemption", "DefaultBinder"}
)

# Builder: (feats, args) -> ScoredPlugin (weight filled by the compiler).
Builder = Callable[[FeaturizedSnapshot, dict], ScoredPlugin]


def _build_node_unschedulable(feats, args):
    from ksim_tpu.plugins.nodeunschedulable import NodeUnschedulable

    return ScoredPlugin(NodeUnschedulable(), score_enabled=False)


def _build_fit(feats, args):
    from ksim_tpu.plugins.noderesources import NodeResourcesFit

    strategy = args.get("scoringStrategy") or {}
    resources = strategy.get("resources") or [
        {"name": "cpu", "weight": 1},
        {"name": "memory", "weight": 1},
    ]
    # All three upstream strategies are valid config (the reference decodes
    # any upstream KubeSchedulerConfiguration, simulator/config/config.go:
    # 275-291, and its tests exercise MostAllocated, config_test.go:30-56);
    # the kernel validates the name and the RTCR shape.
    stype = strategy.get("type") or "LeastAllocated"
    shape = tuple(
        (int(p.get("utilization", 0)), int(p.get("score", 0)))
        for p in (strategy.get("requestedToCapacityRatio") or {}).get("shape") or []
    )
    spec = tuple((r["name"], int(r.get("weight") or 1)) for r in resources)
    return ScoredPlugin(
        NodeResourcesFit(
            feats.resources, score_resources=spec, strategy=stype, shape=shape
        )
    )


def _build_balanced(feats, args):
    from ksim_tpu.plugins.noderesources import NodeResourcesBalancedAllocation

    resources = args.get("resources") or [{"name": "cpu"}, {"name": "memory"}]
    spec = tuple(r["name"] for r in resources)
    return ScoredPlugin(
        NodeResourcesBalancedAllocation(feats.resources, score_resources=spec),
        filter_enabled=False,
    )


def _build_taints(feats, args):
    from ksim_tpu.plugins.tainttoleration import TaintToleration

    return ScoredPlugin(TaintToleration(feats.aux["taints"]))


def _build_node_affinity(feats, args):
    from ksim_tpu.plugins.nodeaffinity import NodeAffinity

    # NodeAffinityArgs.addedAffinity rides the featurizer (profile-level
    # terms in the affinity vocabulary, CompiledProfile.featurizer); the
    # kernel reads the added_terms/added_pref aux fields unconditionally.
    return ScoredPlugin(NodeAffinity())


def _build_spread(feats, args):
    from ksim_tpu.plugins.podtopologyspread import PodTopologySpread

    return ScoredPlugin(PodTopologySpread(feats.aux["spread"]))


def _build_interpod(feats, args):
    from ksim_tpu.plugins.interpodaffinity import InterPodAffinity

    return ScoredPlugin(InterPodAffinity(feats.aux["interpod"]))


def _build_node_name(feats, args):
    from ksim_tpu.plugins.nodename import NodeName

    return ScoredPlugin(NodeName(), score_enabled=False)


def _build_node_ports(feats, args):
    from ksim_tpu.plugins.nodeports import NodePorts

    return ScoredPlugin(NodePorts(), score_enabled=False)


def _build_image_locality(feats, args):
    from ksim_tpu.plugins.imagelocality import ImageLocality

    return ScoredPlugin(
        ImageLocality(feats.aux["imagelocality"]), filter_enabled=False
    )


def _build_volume(cls_name):
    def build(feats, args):
        from ksim_tpu.plugins import volumes

        cls = getattr(volumes, cls_name)
        return ScoredPlugin(cls(feats.aux["volumes"]), score_enabled=False)

    return build


# Legacy registry name -> attachable-volumes-* pool suffix.  Upstream
# v1.30 registers these as one-type non-CSI limit plugins
# (nodevolumelimits/non_csi.go); the reference's exported default config
# enables them in the filter set (snapshot_test.go:1415), so any
# reference-exported snapshot must import here.
LEGACY_VOLUME_LIMITS = {
    "EBSLimits": "aws-ebs",
    "GCEPDLimits": "gce-pd",
    "AzureDiskLimits": "azure-disk",
    "CinderLimits": "cinder",
}


def _build_legacy_volume_limits(name: str, pool: str):
    def build(feats, args):
        from ksim_tpu.plugins.volumes import NodeVolumeLimits

        return ScoredPlugin(
            NodeVolumeLimits(feats.aux["volumes"], name=name, pools=(pool,)),
            score_enabled=False,
        )

    return build


def load_plugin_import(spec: str) -> tuple[Builder, dict, dict]:
    """Resolve a ``pkg.module:attr`` plugin import — the TPU-native form
    of the reference's wasm-plugin loading, where out-of-tree plugins are
    registered purely from configuration (reference
    simulator/scheduler/config/wasm.go:14-58: a pluginConfig arg
    ``guestURL`` names a wasm guest; here ``builderImport`` names an
    importable Builder).

    The attribute may be a Builder ``(feats, args) -> ScoredPlugin``, or
    a dict/object exposing ``builder`` and optionally ``extra_encoders``
    (aux key -> featurizer extra encoder) for plugins that ship their own
    tensors, plus the snapshot-independent QUEUE hooks (upstream runs
    these on the scheduling queue, outside the per-pod cycle, so they
    live on the import target rather than the per-snapshot instance):

    - ``queue_sort_key(pod, priority_of) -> sortable`` — a custom
      QueueSort replacing PrioritySort (the reference wraps custom
      QueueSort plugins, wrappedplugin.go:750-765; upstream allows
      exactly one per profile);
    - ``pre_enqueue(pod) -> str | None`` — a PreEnqueue gate
      (wrappedplugin.go:376): a non-None message keeps the pod out of
      the scheduling queue, like an unsatisfied scheduling gate.

    A non-empty ``KSIM_ALLOWED_PLUGIN_MODULES`` (comma-separated module
    prefixes) narrows the trust gate from all-or-nothing to an operator
    allowlist: only modules equal to or under a listed prefix may load
    (the closest Python analogue to the reference confining wasm guests
    to the configured guestURL sandbox, wasm.go:14-58)."""
    import importlib

    mod, sep, attr = spec.partition(":")
    if not sep or not mod or not attr:
        raise ValueError(
            f"plugin import {spec!r} must look like 'pkg.module:attr'"
        )
    allowlist = [
        p.strip()
        for p in os.environ.get("KSIM_ALLOWED_PLUGIN_MODULES", "").split(",")
        if p.strip()
    ]
    if allowlist and not any(
        mod == p or mod.startswith(p + ".") for p in allowlist
    ):
        raise ValueError(
            f"plugin import {spec!r}: module {mod!r} is not in "
            "KSIM_ALLOWED_PLUGIN_MODULES"
        )
    try:
        target = getattr(importlib.import_module(mod), attr)
    except (ImportError, AttributeError) as e:
        raise ValueError(f"cannot load plugin import {spec!r}: {e}") from e
    if isinstance(target, dict):
        builder = target.get("builder")
        encoders = target.get("extra_encoders") or {}
        hooks = {
            k: target.get(k)
            for k in ("queue_sort_key", "pre_enqueue")
            if callable(target.get(k))
        }
    else:
        builder = getattr(target, "builder", target)
        encoders = getattr(target, "extra_encoders", None) or {}
        hooks = {
            k: getattr(target, k)
            for k in ("queue_sort_key", "pre_enqueue")
            if callable(getattr(target, k, None))
        }
    if not callable(builder):
        raise ValueError(
            f"plugin import {spec!r} does not provide a callable builder"
        )
    return builder, dict(encoders), hooks


def _load_config_plugins(
    profile_cfg: dict, registry: dict[str, Builder], allow_imports: bool
) -> tuple[dict[str, Builder], dict, dict]:
    """Scan a profile's pluginConfig for ``builderImport`` args and
    register the loaded Builders (before plugin-set merging, like the
    reference registers wasm plugins before config conversion —
    pkg/debuggablescheduler/debuggable_scheduler.go:46-88).  Explicitly
    passed registry entries win over config-loaded ones.

    ``allow_imports`` gates the capability: importing a module executes
    arbitrary code, so only operator-owned configs (boot config, CLI)
    may use it — a config arriving over the debug HTTP API may not,
    unless the operator opted in (service allow_plugin_imports /
    KSIM_ALLOW_PLUGIN_IMPORTS=1).  The reference's wasm guests are
    sandboxed; a Python import is not."""
    encoders: dict = {}
    queue_hooks: dict[str, dict] = {}  # plugin name -> {hook: fn}
    for pc in profile_cfg.get("pluginConfig") or []:
        name = pc.get("name")
        spec = (pc.get("args") or {}).get("builderImport")
        if not name or not spec:
            continue
        if not allow_imports:
            raise ValueError(
                f"pluginConfig {name!r} uses builderImport, which this "
                "config source is not trusted for (enable with "
                "allow_plugin_imports / KSIM_ALLOW_PLUGIN_IMPORTS=1)"
            )
        builder, enc, hooks = load_plugin_import(spec)
        if name not in registry:
            registry[name] = builder
        encoders.update(enc)
        if hooks:
            queue_hooks[name] = hooks
    return registry, encoders, queue_hooks


INTREE_BUILDERS: dict[str, Builder] = {
    "NodeUnschedulable": _build_node_unschedulable,
    "NodeName": _build_node_name,
    "NodeResourcesFit": _build_fit,
    "NodeResourcesBalancedAllocation": _build_balanced,
    "TaintToleration": _build_taints,
    "NodeAffinity": _build_node_affinity,
    "NodePorts": _build_node_ports,
    "PodTopologySpread": _build_spread,
    "InterPodAffinity": _build_interpod,
    "ImageLocality": _build_image_locality,
    "VolumeRestrictions": _build_volume("VolumeRestrictions"),
    "NodeVolumeLimits": _build_volume("NodeVolumeLimits"),
    "VolumeBinding": _build_volume("VolumeBinding"),
    "VolumeZone": _build_volume("VolumeZone"),
    **{
        name: _build_legacy_volume_limits(name, pool)
        for name, pool in LEGACY_VOLUME_LIMITS.items()
    },
}


@dataclass
class CompiledProfile:
    """One profile's kernel set, ready to drive the Engine."""

    scheduler_name: str
    enabled: tuple[tuple[str, int], ...]  # (plugin, weight) in filter order
    plugin_args: dict[str, dict]
    skipped: tuple[str, ...]  # enabled names with no kernel (gap surface)
    registry: dict[str, Builder] = field(default_factory=dict)
    hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
    # Per-extension-point overrides (upstream per-point PluginSets disable
    # a plugin at ONE point, not everywhere).
    filter_disabled: frozenset[str] = frozenset()
    score_disabled: frozenset[str] = frozenset()
    reserve_disabled: frozenset[str] = frozenset()
    prebind_disabled: frozenset[str] = frozenset()
    permit_disabled: frozenset[str] = frozenset()
    postfilter_disabled: frozenset[str] = frozenset()
    bind_disabled: frozenset[str] = frozenset()
    postbind_disabled: frozenset[str] = frozenset()
    # Snapshot-independent queue hooks from config-registered plugins
    # (load_plugin_import): a custom QueueSort replacing PrioritySort
    # (name, key fn), and PreEnqueue gates [(name, fn), ...].
    queue_sort_plugin: "tuple[str, Callable] | None" = None
    pre_enqueue_hooks: tuple = ()
    # KubeSchedulerProfile.percentageOfNodesToScore (v1.30: per-profile
    # override of the global field; None = inherit, 0 = adaptive).  Used
    # only by the opt-in sampling emulation (KSIM_PNTS_EMULATION=1).
    percentage_of_nodes_to_score: int | None = None
    # Plugins added only through a per-point set: name -> points enabled.
    point_only: dict[str, frozenset[str]] = field(default_factory=dict)
    # Featurizer extra encoders shipped by config-loaded plugins
    # (load_plugin_import).
    extra_encoders: dict = field(default_factory=dict)

    def spread_defaults(self) -> tuple | None:
        """PodTopologySpreadArgs -> default-constraint tuple (upstream
        v1 defaults.go: defaultingType defaults to System; List uses the
        args' defaultConstraints; System forbids explicit ones)."""
        from ksim_tpu.state.encoding import SYSTEM_DEFAULT_CONSTRAINTS

        args = self.plugin_args.get("PodTopologySpread", {})
        dtype = args.get("defaultingType") or "System"
        explicit = args.get("defaultConstraints") or []
        if dtype == "System":
            if explicit:
                raise ValueError(
                    "PodTopologySpreadArgs: defaultConstraints must be "
                    "empty when defaultingType is System (upstream "
                    "validation)"
                )
            return SYSTEM_DEFAULT_CONSTRAINTS
        if dtype != "List":
            raise ValueError(
                f"PodTopologySpreadArgs: unknown defaultingType {dtype!r}"
            )
        return tuple(explicit) or None

    def featurizer(self, *, pod_bucket_min: int | None = None) -> Featurizer:
        return Featurizer(
            interpod_hard_weight=self.hard_pod_affinity_weight,
            extra_encoders=self.extra_encoders,
            pod_bucket_min=pod_bucket_min,
            added_affinity=self.plugin_args.get("NodeAffinity", {}).get(
                "addedAffinity"
            ),
            spread_defaults=self.spread_defaults(),
        )

    def plugins(self, feats: FeaturizedSnapshot) -> tuple[ScoredPlugin, ...]:
        """The Engine plugin tuple — the jit-compiled unit.  Rebuilding
        after a config change is the reference's scheduler restart."""
        out = []
        for name, weight in self.enabled:
            builder = self.registry.get(name) or INTREE_BUILDERS.get(name)
            if builder is None:
                continue
            sp = builder(feats, self.plugin_args.get(name, {}))
            filter_on = sp.filter_enabled and name not in self.filter_disabled
            score_on = sp.score_enabled and name not in self.score_disabled

            def host_on(hook: str, disabled: frozenset, point: str) -> bool:
                ext = sp.extender
                has_ext = ext is not None and (
                    getattr(ext, f"before_{hook}", None) is not None
                    or getattr(ext, f"after_{hook}", None) is not None
                )
                on = (hasattr(sp.plugin, hook) or has_ext) and name not in disabled
                if name in self.point_only:
                    on = on and point in self.point_only[name]
                return on

            permit_on = host_on("permit", self.permit_disabled, "permit")
            reserve_host = host_on(
                "reserve", self.reserve_disabled, "reserve"
            ) or host_on("unreserve", self.reserve_disabled, "reserve")
            postfilter_on = host_on(
                "post_filter", self.postfilter_disabled, "postFilter"
            )
            prebind_host = host_on("pre_bind", self.prebind_disabled, "preBind")
            bind_on = host_on("bind", self.bind_disabled, "bind")
            postbind_on = host_on(
                "post_bind", self.postbind_disabled, "postBind"
            )
            def point_on(point: str, disabled: frozenset) -> bool:
                if name in disabled:
                    return False
                if name in self.point_only:
                    return point in self.point_only[name]
                return True

            if name in self.point_only:
                points = self.point_only[name]
                filter_on = filter_on and "filter" in points
                score_on = score_on and "score" in points
            # A host-hook-only plugin stays in the set with both kernel
            # points off: the engine loops skip it, the service still
            # runs its host-side hooks.
            if not (
                filter_on
                or score_on
                or permit_on
                or reserve_host
                or postfilter_on
                or prebind_host
                or bind_on
                or postbind_on
            ):
                continue
            out.append(
                ScoredPlugin(
                    sp.plugin,
                    weight=weight if weight > 0 else 1,
                    filter_enabled=filter_on,
                    score_enabled=score_on,
                    extender=sp.extender,
                    # Point-only plugins are active ONLY at their named
                    # points: prebind_enabled both gates the host
                    # pre_bind hook (service._run_pre_bind) and the
                    # recorded reserve/prebind success maps.
                    reserve_enabled=point_on("reserve", self.reserve_disabled),
                    prebind_enabled=point_on("preBind", self.prebind_disabled),
                    permit_enabled=permit_on,
                    postfilter_enabled=postfilter_on,
                    bind_enabled=bind_on,
                    postbind_enabled=postbind_on,
                )
            )
        return tuple(out)


def _merge_plugin_set(
    defaults: Sequence[tuple[str, int]],
    custom: dict | None,
) -> list[tuple[str, int]]:
    """Upstream mergePluginSet over (name, weight) lists."""
    custom = custom or {}
    disabled = {p.get("name") for p in custom.get("disabled") or []}
    enabled_custom = custom.get("enabled") or []
    overrides = {
        p["name"]: int(p.get("weight") or 0)
        for p in enabled_custom
        if p.get("name")
    }
    merged: list[tuple[str, int]] = []
    replaced: set[str] = set()
    for name, weight in defaults:
        if "*" in disabled or name in disabled:
            continue
        if name in overrides:
            # Upstream replaces the default entry with the custom one
            # wholesale; a nil weight then defaults to 1, NOT the
            # default-profile weight.
            merged.append((name, overrides[name] or 1))
            replaced.add(name)
        else:
            merged.append((name, weight))
    for p in enabled_custom:
        name = p.get("name")
        if name and name not in replaced:
            merged.append((name, int(p.get("weight") or 0)))
    return merged


def compile_profile(
    profile_cfg: dict | None = None,
    *,
    registry: dict[str, Builder] | None = None,
    allow_plugin_imports: bool = False,
) -> CompiledProfile:
    """One KubeSchedulerProfile dict -> CompiledProfile.  Raises ValueError
    on unknown enabled plugins (reference registry behavior) unless they
    are upstream defaults without kernels (recorded in ``skipped``)."""
    profile_cfg = profile_cfg or {}
    # In-code registry entries may be bare Builders or the same
    # dict/object shape load_plugin_import accepts (builder + queue
    # hooks); normalize to Builders + a hook map.
    norm_registry: dict[str, Builder] = {}
    queue_hooks: dict[str, dict] = {}
    for name, entry in (registry or {}).items():
        if callable(entry):
            norm_registry[name] = entry
            continue
        get = entry.get if isinstance(entry, dict) else (
            lambda k, _e=entry: getattr(_e, k, None)
        )
        builder = get("builder")
        if not callable(builder):
            raise ValueError(
                f"registry entry {name!r} does not provide a callable "
                "builder (dict/object entries need 'builder' alongside "
                "their queue hooks)"
            )
        norm_registry[name] = builder
        hooks = {
            k: get(k)
            for k in ("queue_sort_key", "pre_enqueue")
            if callable(get(k))
        }
        if hooks:
            queue_hooks[name] = hooks
    # Config-declared out-of-tree plugins register first (the reference's
    # RegisterWasmPlugins-before-conversion ordering).
    registry, loaded_encoders, loaded_hooks = _load_config_plugins(
        profile_cfg, norm_registry, allow_plugin_imports
    )
    for name, hooks in loaded_hooks.items():
        queue_hooks.setdefault(name, hooks)
    plugins_cfg = profile_cfg.get("plugins") or {}
    merged = _merge_plugin_set(DEFAULT_MULTIPOINT, plugins_cfg.get("multiPoint"))

    # Per-point sets act on ONE extension point: a disable drops the
    # plugin at that point only; an enable adds it at that point only
    # (upstream Plugins struct per-point PluginSets).  Kernel relevance is
    # filter/score; other points are validated but structurally inert.
    default_names = {n for n, _ in DEFAULT_MULTIPOINT}
    filter_off: set[str] = set()
    score_off: set[str] = set()
    reserve_off: set[str] = set()
    prebind_off: set[str] = set()
    permit_off: set[str] = set()
    postfilter_off: set[str] = set()
    bind_off: set[str] = set()
    postbind_off: set[str] = set()
    point_only: dict[str, set[str]] = {}
    for point in ("queueSort", "preEnqueue", "preFilter", "filter",
                  "postFilter", "preScore", "score", "reserve", "permit",
                  "preBind", "bind", "postBind"):
        point_cfg = plugins_cfg.get(point)
        if not point_cfg:
            continue
        have = {n for n, _ in merged}
        disabled_here = {p.get("name") for p in point_cfg.get("disabled") or []}
        if point == "filter":
            filter_off |= have if "*" in disabled_here else disabled_here
        elif point == "score":
            score_off |= have if "*" in disabled_here else disabled_here
        elif point == "reserve":
            reserve_off |= have if "*" in disabled_here else disabled_here
        elif point == "preBind":
            prebind_off |= have if "*" in disabled_here else disabled_here
        elif point == "permit":
            permit_off |= have if "*" in disabled_here else disabled_here
        elif point == "postFilter":
            postfilter_off |= have if "*" in disabled_here else disabled_here
        elif point == "bind":
            bind_off |= have if "*" in disabled_here else disabled_here
        elif point == "postBind":
            postbind_off |= have if "*" in disabled_here else disabled_here
        for p in point_cfg.get("enabled") or []:
            name = p.get("name")
            if not name:
                continue
            if name not in have and name not in default_names:
                if name not in registry and name not in INTREE_BUILDERS:
                    raise ValueError(f"unknown plugin {name!r} enabled at {point}")
            if name not in have:
                merged.append((name, int(p.get("weight") or 0)))
                have.add(name)
                point_only[name] = set()
            if name in point_only:
                point_only[name].add(point)
            elif point == "score" and p.get("weight"):
                # Re-weighting an already-enabled plugin at the score point.
                merged = [
                    (n, int(p["weight"]) if n == name else w) for n, w in merged
                ]

    plugin_args: dict[str, dict] = {}
    for pc in profile_cfg.get("pluginConfig") or []:
        name = pc.get("name")
        if name:
            plugin_args[name] = dict(pc.get("args") or {})

    skipped = tuple(
        n
        for n, _ in merged
        if n not in INTREE_BUILDERS
        and n not in (registry or {})
        and n not in STRUCTURAL_PLUGINS
    )
    for name in skipped:
        if name not in default_names:
            raise ValueError(f"unknown plugin {name!r} in profile")
        logger.warning("plugin %s has no kernel yet; skipping", name)

    hard_weight = int(
        plugin_args.get("InterPodAffinity", {}).get(
            "hardPodAffinityWeight", DEFAULT_HARD_POD_AFFINITY_WEIGHT
        )
    )
    # Queue hooks activate for ENABLED plugins only.  A plugin shipping
    # queue_sort_key replaces PrioritySort's order for the profile;
    # upstream allows exactly one QueueSort plugin per profile
    # (wrappedplugin.go:357 "There must be only one in each profile").
    enabled_names = {n for n, _ in merged}
    sorters = [
        (n, h["queue_sort_key"])
        for n, h in queue_hooks.items()
        if n in enabled_names and "queue_sort_key" in h
    ]
    if len(sorters) > 1:
        raise ValueError(
            "multiple queue-sort plugins enabled: "
            + ", ".join(sorted(n for n, _ in sorters))
        )
    pre_enqueue_hooks = tuple(
        (n, h["pre_enqueue"])
        for n, h in sorted(queue_hooks.items())
        if n in enabled_names and "pre_enqueue" in h
    )
    prof = CompiledProfile(
        scheduler_name=profile_cfg.get("schedulerName") or DEFAULT_SCHEDULER_NAME,
        enabled=tuple(merged),
        plugin_args=plugin_args,
        skipped=skipped,
        registry=dict(registry or {}),
        hard_pod_affinity_weight=hard_weight,
        filter_disabled=frozenset(filter_off),
        score_disabled=frozenset(score_off),
        reserve_disabled=frozenset(reserve_off),
        prebind_disabled=frozenset(prebind_off),
        permit_disabled=frozenset(permit_off),
        postfilter_disabled=frozenset(postfilter_off),
        bind_disabled=frozenset(bind_off),
        postbind_disabled=frozenset(postbind_off),
        point_only={k: frozenset(v) for k, v in point_only.items()},
        extra_encoders=loaded_encoders,
        queue_sort_plugin=sorters[0] if sorters else None,
        pre_enqueue_hooks=pre_enqueue_hooks,
        percentage_of_nodes_to_score=(
            int(profile_cfg["percentageOfNodesToScore"])
            if isinstance(profile_cfg.get("percentageOfNodesToScore"), int)
            else None
        ),
    )
    prof.spread_defaults()  # validate PodTopologySpreadArgs at compile time
    return prof


def compile_configuration(
    cfg: dict | None,
    *,
    registry: dict[str, Builder] | None = None,
    allow_plugin_imports: bool = False,
) -> list[CompiledProfile]:
    """KubeSchedulerConfiguration dict -> compiled profiles (defaulting to
    one default-scheduler profile, reference scheduler.go:143-150)."""
    cfg = cfg or {}
    profiles = cfg.get("profiles") or [{}]
    return [
        compile_profile(
            p, registry=registry, allow_plugin_imports=allow_plugin_imports
        )
        for p in profiles
    ]
