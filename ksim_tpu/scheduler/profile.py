"""KubeSchedulerConfiguration -> compiled kernel profiles.

The reference "compiles" a profile by rewriting the scheduler's
KubeSchedulerConfiguration (wrap every plugin, merge plugin sets, disable
MultiPoint defaults) and restarting the scheduler container (reference
simulator/scheduler/scheduler.go:141-183 ConvertConfigurationForSimulator,
simulator/scheduler/plugin/plugins.go:174-304 ConvertForSimulator/
mergePluginSet/getScorePluginWeight).  The TPU analogue: select + configure
the kernel set for the Engine — "restart" is re-jitting with a new plugin
tuple (Engine construction), with rollback on a config that fails to
compile.

Merge semantics mirror upstream default_plugins.go mergePluginSet:

- start from the default MultiPoint list (order defines filter order and
  therefore early-exit recording order);
- ``disabled`` entries remove by name, ``"*"`` removes all defaults;
- ``enabled`` entries already in the defaults override the weight in
  place; new names append in declaration order;
- the per-extension-point sets (filter/score/...) then enable/disable on
  top, for out-of-tree or re-weighted plugins.

Plugin args honored from pluginConfig (upstream *Args types):
``NodeResourcesFitArgs.scoringStrategy`` (LeastAllocated resources),
``NodeResourcesBalancedAllocationArgs.resources``,
``InterPodAffinityArgs.hardPodAffinityWeight`` (threaded into the
featurizer's inter-pod encoding).

Every upstream default-profile plugin resolves: kernels for the filter/
score families (including the volume family), STRUCTURAL handling in the
service for PrioritySort (queue sort with PriorityClass resolution),
DefaultBinder (bind), DefaultPreemption (postfilter), and SchedulingGates
(queue gate).  Truly unknown names raise; anything enabled without a
kernel would surface through ``CompiledProfile.skipped``.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from ksim_tpu.engine.core import ScoredPlugin
from ksim_tpu.state.featurizer import FeaturizedSnapshot, Featurizer
from ksim_tpu.state.interpod import DEFAULT_HARD_POD_AFFINITY_WEIGHT

logger = logging.getLogger(__name__)

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# Upstream v1.30 getDefaultPlugins MultiPoint order and weights
# (pkg/scheduler/apis/config/v1/default_plugins.go).
DEFAULT_MULTIPOINT: tuple[tuple[str, int], ...] = (
    ("SchedulingGates", 0),
    ("PrioritySort", 0),
    ("NodeUnschedulable", 0),
    ("NodeName", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("VolumeRestrictions", 0),
    ("NodeVolumeLimits", 0),
    ("VolumeBinding", 0),
    ("VolumeZone", 0),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("DefaultPreemption", 0),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("DefaultBinder", 0),
)

# Plugins realized outside the kernel set.
STRUCTURAL_PLUGINS = frozenset(
    {"SchedulingGates", "PrioritySort", "DefaultPreemption", "DefaultBinder"}
)

# Builder: (feats, args) -> ScoredPlugin (weight filled by the compiler).
Builder = Callable[[FeaturizedSnapshot, dict], ScoredPlugin]


def _build_node_unschedulable(feats, args):
    from ksim_tpu.plugins.nodeunschedulable import NodeUnschedulable

    return ScoredPlugin(NodeUnschedulable(), score_enabled=False)


def _build_fit(feats, args):
    from ksim_tpu.plugins.noderesources import NodeResourcesFit

    strategy = args.get("scoringStrategy") or {}
    resources = strategy.get("resources") or [
        {"name": "cpu", "weight": 1},
        {"name": "memory", "weight": 1},
    ]
    stype = strategy.get("type", "LeastAllocated")
    if stype != "LeastAllocated":
        raise ValueError(
            f"NodeResourcesFit scoringStrategy {stype!r} not supported "
            "(LeastAllocated only)"
        )
    spec = tuple((r["name"], int(r.get("weight", 1))) for r in resources)
    return ScoredPlugin(NodeResourcesFit(feats.resources, score_resources=spec))


def _build_balanced(feats, args):
    from ksim_tpu.plugins.noderesources import NodeResourcesBalancedAllocation

    resources = args.get("resources") or [{"name": "cpu"}, {"name": "memory"}]
    spec = tuple(r["name"] for r in resources)
    return ScoredPlugin(
        NodeResourcesBalancedAllocation(feats.resources, score_resources=spec),
        filter_enabled=False,
    )


def _build_taints(feats, args):
    from ksim_tpu.plugins.tainttoleration import TaintToleration

    return ScoredPlugin(TaintToleration(feats.aux["taints"]))


def _build_node_affinity(feats, args):
    from ksim_tpu.plugins.nodeaffinity import NodeAffinity

    if args.get("addedAffinity"):
        raise ValueError("NodeAffinityArgs.addedAffinity is not supported yet")
    return ScoredPlugin(NodeAffinity())


def _build_spread(feats, args):
    from ksim_tpu.plugins.podtopologyspread import PodTopologySpread

    return ScoredPlugin(PodTopologySpread(feats.aux["spread"]))


def _build_interpod(feats, args):
    from ksim_tpu.plugins.interpodaffinity import InterPodAffinity

    return ScoredPlugin(InterPodAffinity(feats.aux["interpod"]))


def _build_node_name(feats, args):
    from ksim_tpu.plugins.nodename import NodeName

    return ScoredPlugin(NodeName(), score_enabled=False)


def _build_node_ports(feats, args):
    from ksim_tpu.plugins.nodeports import NodePorts

    return ScoredPlugin(NodePorts(), score_enabled=False)


def _build_image_locality(feats, args):
    from ksim_tpu.plugins.imagelocality import ImageLocality

    return ScoredPlugin(
        ImageLocality(feats.aux["imagelocality"]), filter_enabled=False
    )


def _build_volume(cls_name):
    def build(feats, args):
        from ksim_tpu.plugins import volumes

        cls = getattr(volumes, cls_name)
        return ScoredPlugin(cls(feats.aux["volumes"]), score_enabled=False)

    return build


def load_plugin_import(spec: str) -> tuple[Builder, dict]:
    """Resolve a ``pkg.module:attr`` plugin import — the TPU-native form
    of the reference's wasm-plugin loading, where out-of-tree plugins are
    registered purely from configuration (reference
    simulator/scheduler/config/wasm.go:14-58: a pluginConfig arg
    ``guestURL`` names a wasm guest; here ``builderImport`` names an
    importable Builder).

    The attribute may be a Builder ``(feats, args) -> ScoredPlugin``, or
    a dict/object exposing ``builder`` and optionally ``extra_encoders``
    (aux key -> featurizer extra encoder) for plugins that ship their own
    tensors.

    A non-empty ``KSIM_ALLOWED_PLUGIN_MODULES`` (comma-separated module
    prefixes) narrows the trust gate from all-or-nothing to an operator
    allowlist: only modules equal to or under a listed prefix may load
    (the closest Python analogue to the reference confining wasm guests
    to the configured guestURL sandbox, wasm.go:14-58)."""
    import importlib

    mod, sep, attr = spec.partition(":")
    if not sep or not mod or not attr:
        raise ValueError(
            f"plugin import {spec!r} must look like 'pkg.module:attr'"
        )
    allowlist = [
        p.strip()
        for p in os.environ.get("KSIM_ALLOWED_PLUGIN_MODULES", "").split(",")
        if p.strip()
    ]
    if allowlist and not any(
        mod == p or mod.startswith(p + ".") for p in allowlist
    ):
        raise ValueError(
            f"plugin import {spec!r}: module {mod!r} is not in "
            "KSIM_ALLOWED_PLUGIN_MODULES"
        )
    try:
        target = getattr(importlib.import_module(mod), attr)
    except (ImportError, AttributeError) as e:
        raise ValueError(f"cannot load plugin import {spec!r}: {e}") from e
    if isinstance(target, dict):
        builder = target.get("builder")
        encoders = target.get("extra_encoders") or {}
    else:
        builder = getattr(target, "builder", target)
        encoders = getattr(target, "extra_encoders", None) or {}
    if not callable(builder):
        raise ValueError(
            f"plugin import {spec!r} does not provide a callable builder"
        )
    return builder, dict(encoders)


def _load_config_plugins(
    profile_cfg: dict, registry: dict[str, Builder], allow_imports: bool
) -> tuple[dict[str, Builder], dict]:
    """Scan a profile's pluginConfig for ``builderImport`` args and
    register the loaded Builders (before plugin-set merging, like the
    reference registers wasm plugins before config conversion —
    pkg/debuggablescheduler/debuggable_scheduler.go:46-88).  Explicitly
    passed registry entries win over config-loaded ones.

    ``allow_imports`` gates the capability: importing a module executes
    arbitrary code, so only operator-owned configs (boot config, CLI)
    may use it — a config arriving over the debug HTTP API may not,
    unless the operator opted in (service allow_plugin_imports /
    KSIM_ALLOW_PLUGIN_IMPORTS=1).  The reference's wasm guests are
    sandboxed; a Python import is not."""
    encoders: dict = {}
    for pc in profile_cfg.get("pluginConfig") or []:
        name = pc.get("name")
        spec = (pc.get("args") or {}).get("builderImport")
        if not name or not spec:
            continue
        if not allow_imports:
            raise ValueError(
                f"pluginConfig {name!r} uses builderImport, which this "
                "config source is not trusted for (enable with "
                "allow_plugin_imports / KSIM_ALLOW_PLUGIN_IMPORTS=1)"
            )
        builder, enc = load_plugin_import(spec)
        if name not in registry:
            registry[name] = builder
        encoders.update(enc)
    return registry, encoders


INTREE_BUILDERS: dict[str, Builder] = {
    "NodeUnschedulable": _build_node_unschedulable,
    "NodeName": _build_node_name,
    "NodeResourcesFit": _build_fit,
    "NodeResourcesBalancedAllocation": _build_balanced,
    "TaintToleration": _build_taints,
    "NodeAffinity": _build_node_affinity,
    "NodePorts": _build_node_ports,
    "PodTopologySpread": _build_spread,
    "InterPodAffinity": _build_interpod,
    "ImageLocality": _build_image_locality,
    "VolumeRestrictions": _build_volume("VolumeRestrictions"),
    "NodeVolumeLimits": _build_volume("NodeVolumeLimits"),
    "VolumeBinding": _build_volume("VolumeBinding"),
    "VolumeZone": _build_volume("VolumeZone"),
}


@dataclass
class CompiledProfile:
    """One profile's kernel set, ready to drive the Engine."""

    scheduler_name: str
    enabled: tuple[tuple[str, int], ...]  # (plugin, weight) in filter order
    plugin_args: dict[str, dict]
    skipped: tuple[str, ...]  # enabled names with no kernel (gap surface)
    registry: dict[str, Builder] = field(default_factory=dict)
    hard_pod_affinity_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
    # Per-extension-point overrides (upstream per-point PluginSets disable
    # a plugin at ONE point, not everywhere).
    filter_disabled: frozenset[str] = frozenset()
    score_disabled: frozenset[str] = frozenset()
    reserve_disabled: frozenset[str] = frozenset()
    prebind_disabled: frozenset[str] = frozenset()
    permit_disabled: frozenset[str] = frozenset()
    # Plugins added only through a per-point set: name -> points enabled.
    point_only: dict[str, frozenset[str]] = field(default_factory=dict)
    # Featurizer extra encoders shipped by config-loaded plugins
    # (load_plugin_import).
    extra_encoders: dict = field(default_factory=dict)

    def featurizer(self, *, pod_bucket_min: int | None = None) -> Featurizer:
        return Featurizer(
            interpod_hard_weight=self.hard_pod_affinity_weight,
            extra_encoders=self.extra_encoders,
            pod_bucket_min=pod_bucket_min,
        )

    def plugins(self, feats: FeaturizedSnapshot) -> tuple[ScoredPlugin, ...]:
        """The Engine plugin tuple — the jit-compiled unit.  Rebuilding
        after a config change is the reference's scheduler restart."""
        out = []
        for name, weight in self.enabled:
            builder = self.registry.get(name) or INTREE_BUILDERS.get(name)
            if builder is None:
                continue
            sp = builder(feats, self.plugin_args.get(name, {}))
            filter_on = sp.filter_enabled and name not in self.filter_disabled
            score_on = sp.score_enabled and name not in self.score_disabled
            permit_on = (
                hasattr(sp.plugin, "permit") and name not in self.permit_disabled
            )
            if name in self.point_only:
                points = self.point_only[name]
                filter_on = filter_on and "filter" in points
                score_on = score_on and "score" in points
                permit_on = permit_on and "permit" in points
            # A permit-only plugin stays in the set with both kernel
            # points off: the engine loops skip it, the service still
            # runs its host-side permit hook.
            if not filter_on and not score_on and not permit_on:
                continue
            out.append(
                ScoredPlugin(
                    sp.plugin,
                    weight=weight if weight > 0 else 1,
                    filter_enabled=filter_on,
                    score_enabled=score_on,
                    reserve_enabled=name not in self.reserve_disabled,
                    prebind_enabled=name not in self.prebind_disabled,
                    permit_enabled=permit_on,
                )
            )
        return tuple(out)


def _merge_plugin_set(
    defaults: Sequence[tuple[str, int]],
    custom: dict | None,
) -> list[tuple[str, int]]:
    """Upstream mergePluginSet over (name, weight) lists."""
    custom = custom or {}
    disabled = {p.get("name") for p in custom.get("disabled") or []}
    enabled_custom = custom.get("enabled") or []
    overrides = {
        p["name"]: int(p.get("weight") or 0)
        for p in enabled_custom
        if p.get("name")
    }
    merged: list[tuple[str, int]] = []
    replaced: set[str] = set()
    for name, weight in defaults:
        if "*" in disabled or name in disabled:
            continue
        if name in overrides:
            # Upstream replaces the default entry with the custom one
            # wholesale; a nil weight then defaults to 1, NOT the
            # default-profile weight.
            merged.append((name, overrides[name] or 1))
            replaced.add(name)
        else:
            merged.append((name, weight))
    for p in enabled_custom:
        name = p.get("name")
        if name and name not in replaced:
            merged.append((name, int(p.get("weight") or 0)))
    return merged


def compile_profile(
    profile_cfg: dict | None = None,
    *,
    registry: dict[str, Builder] | None = None,
    allow_plugin_imports: bool = False,
) -> CompiledProfile:
    """One KubeSchedulerProfile dict -> CompiledProfile.  Raises ValueError
    on unknown enabled plugins (reference registry behavior) unless they
    are upstream defaults without kernels (recorded in ``skipped``)."""
    profile_cfg = profile_cfg or {}
    # Config-declared out-of-tree plugins register first (the reference's
    # RegisterWasmPlugins-before-conversion ordering).
    registry, loaded_encoders = _load_config_plugins(
        profile_cfg, dict(registry or {}), allow_plugin_imports
    )
    plugins_cfg = profile_cfg.get("plugins") or {}
    merged = _merge_plugin_set(DEFAULT_MULTIPOINT, plugins_cfg.get("multiPoint"))

    # Per-point sets act on ONE extension point: a disable drops the
    # plugin at that point only; an enable adds it at that point only
    # (upstream Plugins struct per-point PluginSets).  Kernel relevance is
    # filter/score; other points are validated but structurally inert.
    default_names = {n for n, _ in DEFAULT_MULTIPOINT}
    filter_off: set[str] = set()
    score_off: set[str] = set()
    reserve_off: set[str] = set()
    prebind_off: set[str] = set()
    permit_off: set[str] = set()
    point_only: dict[str, set[str]] = {}
    for point in ("preFilter", "filter", "postFilter", "preScore", "score",
                  "reserve", "permit", "preBind", "bind", "postBind"):
        point_cfg = plugins_cfg.get(point)
        if not point_cfg:
            continue
        have = {n for n, _ in merged}
        disabled_here = {p.get("name") for p in point_cfg.get("disabled") or []}
        if point == "filter":
            filter_off |= have if "*" in disabled_here else disabled_here
        elif point == "score":
            score_off |= have if "*" in disabled_here else disabled_here
        elif point == "reserve":
            reserve_off |= have if "*" in disabled_here else disabled_here
        elif point == "preBind":
            prebind_off |= have if "*" in disabled_here else disabled_here
        elif point == "permit":
            permit_off |= have if "*" in disabled_here else disabled_here
        for p in point_cfg.get("enabled") or []:
            name = p.get("name")
            if not name:
                continue
            if name not in have and name not in default_names:
                if name not in registry and name not in INTREE_BUILDERS:
                    raise ValueError(f"unknown plugin {name!r} enabled at {point}")
            if name not in have:
                merged.append((name, int(p.get("weight") or 0)))
                have.add(name)
                point_only[name] = set()
            if name in point_only:
                point_only[name].add(point)
            elif point == "score" and p.get("weight"):
                # Re-weighting an already-enabled plugin at the score point.
                merged = [
                    (n, int(p["weight"]) if n == name else w) for n, w in merged
                ]

    plugin_args: dict[str, dict] = {}
    for pc in profile_cfg.get("pluginConfig") or []:
        name = pc.get("name")
        if name:
            plugin_args[name] = dict(pc.get("args") or {})

    skipped = tuple(
        n
        for n, _ in merged
        if n not in INTREE_BUILDERS
        and n not in (registry or {})
        and n not in STRUCTURAL_PLUGINS
    )
    for name in skipped:
        if name not in default_names:
            raise ValueError(f"unknown plugin {name!r} in profile")
        logger.warning("plugin %s has no kernel yet; skipping", name)

    hard_weight = int(
        plugin_args.get("InterPodAffinity", {}).get(
            "hardPodAffinityWeight", DEFAULT_HARD_POD_AFFINITY_WEIGHT
        )
    )
    return CompiledProfile(
        scheduler_name=profile_cfg.get("schedulerName") or DEFAULT_SCHEDULER_NAME,
        enabled=tuple(merged),
        plugin_args=plugin_args,
        skipped=skipped,
        registry=dict(registry or {}),
        hard_pod_affinity_weight=hard_weight,
        filter_disabled=frozenset(filter_off),
        score_disabled=frozenset(score_off),
        reserve_disabled=frozenset(reserve_off),
        prebind_disabled=frozenset(prebind_off),
        permit_disabled=frozenset(permit_off),
        point_only={k: frozenset(v) for k, v in point_only.items()},
        extra_encoders=loaded_encoders,
    )


def compile_configuration(
    cfg: dict | None,
    *,
    registry: dict[str, Builder] | None = None,
    allow_plugin_imports: bool = False,
) -> list[CompiledProfile]:
    """KubeSchedulerConfiguration dict -> compiled profiles (defaulting to
    one default-scheduler profile, reference scheduler.go:143-150)."""
    cfg = cfg or {}
    profiles = cfg.get("profiles") or [{}]
    return [
        compile_profile(
            p, registry=registry, allow_plugin_imports=allow_plugin_imports
        )
        for p in profiles
    ]
