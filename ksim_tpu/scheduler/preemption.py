"""DefaultPreemption (PostFilter) — the victim-search semantics.

Upstream kube-scheduler v1.30 ``plugins/defaultpreemption/default_preemption.go``
and ``framework/preemption/preemption.go``; the reference wraps PostFilter
and records ``{node: {plugin: "preemption victim"}}`` for the nominated
node, ``{}`` for every other filtered node (reference
simulator/scheduler/plugin/wrappedplugin.go:550-577,
simulator/scheduler/plugin/resultstore/store.go:439-456).

This module is the HOST implementation and the parity source of truth:
the per-pass scheduling path runs it directly, with the exact-parity
oracle for fit checks (plugins/oracle.py).  Since round 7 the
device-resident replay (engine/replay.py) lowers the same search into
the segment scan — bounded candidate/reprieve loops through the
compiled filter kernels — gated on the profile's filter set matching
``ORACLE_FIT_FILTER_NAMES`` below, and verified against this module on
the hand-derived fixtures (tests/fixtures/preemption_victims.py).
Changing any semantics here must change the device lowering and the
fixtures together.  Simplifications vs upstream, documented: no
PodDisruptionBudgets in the snapshot model (the reference's 7-kind
snapshot has none either, snapshot/snapshot.go:33-42), so the
PDB-violation criteria are trivially zero; victim start times fall back
to creationTimestamp when status.startTime is absent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

from ksim_tpu.plugins import oracle
from ksim_tpu.state.resources import JSON, name_of, namespace_of

DEFAULT_PREEMPTION = "DefaultPreemption"
NOMINATED_MESSAGE = "preemption victim"

# Upstream DefaultPreemptionArgs defaults.
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100

# The filter chain _FitState.fits runs, BY KERNEL NAME.  The device
# replay's on-device victim search (engine/replay.py) re-checks fits
# through the profile's compiled filter kernels, which is only exact
# when the profile's filter set matches this chain — the lowering gates
# on it.  The volume filters are in fits() too but pass trivially for
# the device vocabulary (no volume objects / no pod volumes), so their
# presence in a profile is allowed but not required.
ORACLE_FIT_FILTER_NAMES = frozenset(
    {
        "NodeUnschedulable",
        "NodeName",
        "TaintToleration",
        "NodeAffinity",
        "NodePorts",
        "NodeResourcesFit",
        "PodTopologySpread",
        "InterPodAffinity",
    }
)
VOLUME_FIT_FILTER_NAMES = frozenset(
    {"VolumeRestrictions", "NodeVolumeLimits", "VolumeBinding", "VolumeZone"}
)


def candidate_count(n_nodes: int) -> int:
    """Upstream GetOffsetAndNumCandidates: how many candidate nodes the
    dry-run collects before stopping (10% of nodes, at least 100,
    capped at the node count)."""
    return min(
        max(n_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100, MIN_CANDIDATE_NODES_ABSOLUTE),
        n_nodes,
    )


def pod_priority(pod: JSON) -> int:
    """Bare spec.priority (callers wanting PriorityClass resolution pass
    a resolver from state/priorities.py as ``priority_of``)."""
    return int(pod.get("spec", {}).get("priority") or 0)


def pod_eligible_to_preempt(pod: JSON) -> bool:
    """PodEligibleToPreemptOthers: preemptionPolicy Never opts out."""
    policy = pod.get("spec", {}).get("preemptionPolicy") or "PreemptLowerPriority"
    return policy != "Never"


def start_time(pod: JSON) -> str:
    """Victim start time: status.startTime, falling back to
    creationTimestamp (module docstring).  Public: the device lowering
    ranks start strings with this exact function."""
    return (
        pod.get("status", {}).get("startTime")
        or pod.get("metadata", {}).get("creationTimestamp")
        or ""
    )


_start_time = start_time  # internal alias (historic name)


def more_important_key(p: JSON, priority_of=pod_priority) -> tuple:
    """Sort key for util.MoreImportantPod order: higher priority first,
    then earlier start time (namespace/name breaks exact ties
    deterministically).  Public: the device lowering pre-ranks the pod
    universe with this exact key."""
    return (-priority_of(p), _start_time(p), namespace_of(p), name_of(p))


_more_important = more_important_key  # internal alias (historic name)


def _pods_by_node(pods: Sequence[JSON]) -> dict[str, list[JSON]]:
    out: dict[str, list[JSON]] = {}
    for p in pods:
        node = p.get("spec", {}).get("nodeName")
        if not node:
            continue
        if p.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            continue
        out.setdefault(node, []).append(p)
    return out


class _FitState:
    """Incremental hypothetical cluster state for repeated fit checks
    while victims are removed/reprieved (upstream mutates a copied
    NodeInfo via RemovePod/AddPod rather than rebuilding the snapshot)."""

    def __init__(
        self,
        nodes: Sequence[JSON],
        cluster_pods: Sequence[JSON],
        namespaces: Sequence[JSON],
        volumes: dict | None = None,
    ) -> None:
        self.nodes = nodes
        self.namespaces = namespaces
        self.volumes = volumes or {"pvs": (), "pvcs": (), "storage_classes": ()}
        # The volume oracle filters rebuild per-call lookup maps over the
        # pvc/pv/sc lists; skip them wholesale when the cluster has no
        # volume objects (the common case for preemption).
        self._check_volumes = bool(
            self.volumes.get("pvcs") or self.volumes.get("pvs")
        )
        self.infos = oracle.build_node_infos(nodes, cluster_pods)
        self._by_name = {info["name"]: info for info in self.infos}
        self.pbn = _pods_by_node(cluster_pods)

    def _info_of(self, pod: JSON):
        return self._by_name.get(pod.get("spec", {}).get("nodeName", ""))

    def remove(self, pod: JSON) -> None:
        from ksim_tpu.state.resources import pod_requests

        info = self._info_of(pod)
        if info is None:
            return
        for r, v in pod_requests(pod).items():
            info["requested"][r] = info["requested"].get(r, 0) - v
        for r, v in pod_requests(pod, non_zero=True).items():
            info["nonzero_requested"][r] = info["nonzero_requested"].get(r, 0) - v
        info["pod_count"] -= 1
        key = (namespace_of(pod), name_of(pod))
        self.pbn[info["name"]] = [
            p
            for p in self.pbn.get(info["name"], [])
            if (namespace_of(p), name_of(p)) != key
        ]

    def add(self, pod: JSON) -> None:
        from ksim_tpu.state.resources import pod_requests

        info = self._info_of(pod)
        if info is None:
            return
        for r, v in pod_requests(pod).items():
            info["requested"][r] = info["requested"].get(r, 0) + v
        for r, v in pod_requests(pod, non_zero=True).items():
            info["nonzero_requested"][r] = info["nonzero_requested"].get(r, 0) + v
        info["pod_count"] += 1
        self.pbn.setdefault(info["name"], []).append(pod)

    def fits(self, pod: JSON, node_idx: int) -> bool:
        """Full default-profile filter check of ``pod`` on one node
        (oracle semantics — exact upstream math)."""
        info = self.infos[node_idx]
        if oracle.node_unschedulable_filter(pod, info):
            return False
        if oracle.node_name_filter(pod, info):
            return False
        if oracle.taint_toleration_filter(pod, info):
            return False
        if oracle.node_affinity_filter(pod, info):
            return False
        if oracle.node_ports_filter(pod, self.pbn.get(info["name"], [])):
            return False
        if oracle.fit_filter(pod, info):
            return False
        if self._check_volumes or pod.get("spec", {}).get("volumes"):
            vols = self.volumes
            node = self.nodes[node_idx]
            on_node = self.pbn.get(info["name"], [])
            if oracle.volume_restrictions_filter(pod, on_node, vols["pvcs"]):
                return False
            if oracle.node_volume_limits_filter(
                pod, node, on_node, vols["pvcs"], vols["pvs"], vols["storage_classes"]
            ):
                return False
            if oracle.volume_binding_filter(
                pod, node, vols["pvcs"], vols["pvs"], vols["storage_classes"]
            ):
                return False
            if oracle.volume_zone_filter(pod, node, vols["pvcs"], vols["pvs"]):
                return False
        if oracle.topology_spread_filter_all(pod, self.infos, self.pbn)[node_idx]:
            return False
        if oracle.inter_pod_affinity_filter_all(
            pod, self.infos, self.pbn, self.namespaces
        )[node_idx]:
            return False
        return True


@dataclass
class Candidate:
    node_index: int
    node_name: str
    victims: list[JSON]  # in MoreImportantPod order


@dataclass
class PreemptionDecision:
    nominated_node: str | None  # None = preemption failed
    victims: list[JSON]


def _select_victims_on_node(
    pod: JSON,
    node_idx: int,
    nodes: Sequence[JSON],
    cluster_pods: Sequence[JSON],
    namespaces: Sequence[JSON],
    volumes: dict | None = None,
    priority_of=pod_priority,
) -> list[JSON] | None:
    """Upstream selectVictimsOnNode: remove all lower-priority pods, check
    feasibility, then reprieve as many as possible in importance order.
    Returns the victim list, or None when the node is not a candidate."""
    node_name = name_of(nodes[node_idx])
    prio = priority_of(pod)
    potential = [
        p
        for p in cluster_pods
        if p.get("spec", {}).get("nodeName") == node_name
        and p.get("status", {}).get("phase") not in ("Succeeded", "Failed")
        and priority_of(p) < prio
    ]
    if not potential:
        return None
    state = _FitState(nodes, cluster_pods, namespaces, volumes)
    for v in potential:
        state.remove(v)
    if not state.fits(pod, node_idx):
        return None
    victims: list[JSON] = []
    # Reprieve in MoreImportantPod order (no PDBs -> single bucket).
    for v in sorted(potential, key=lambda p: _more_important(p, priority_of)):
        state.add(v)
        if not state.fits(pod, node_idx):
            state.remove(v)
            victims.append(v)
    return victims


def _pick_one_node(candidates: list[Candidate], priority_of=pod_priority) -> Candidate:
    """Upstream pickOneNodeForPreemption, PDB criteria degenerate:
    lowest highest-victim-priority, then smallest priority sum, then
    fewest victims, then latest earliest victim start time, then first."""
    best = candidates

    def narrow(keyfn, take_min=True):
        nonlocal best
        vals = [keyfn(c) for c in best]
        target = min(vals) if take_min else max(vals)
        best = [c for c, v in zip(best, vals) if v == target]

    def earliest_high_priority_start(c: Candidate) -> str:
        """util.GetEarliestPodStartTime: the earliest start time among the
        HIGHEST-priority victims only."""
        if not c.victims:
            return ""
        top = max(priority_of(v) for v in c.victims)
        return min(_start_time(v) for v in c.victims if priority_of(v) == top)

    narrow(lambda c: max((priority_of(v) for v in c.victims), default=-(2**31)))
    if len(best) > 1:
        narrow(lambda c: sum(priority_of(v) for v in c.victims))
    if len(best) > 1:
        narrow(lambda c: len(c.victims))
    if len(best) > 1:
        narrow(earliest_high_priority_start, take_min=False)
    return best[0]


def find_preemption(
    pod: JSON,
    nodes: Sequence[JSON],
    cluster_pods: Sequence[JSON],
    *,
    candidate_mask: Sequence[bool] | None = None,
    namespaces: Sequence[JSON] = (),
    volumes: dict | None = None,
    priority_of=pod_priority,
) -> PreemptionDecision:
    """DefaultPreemption for one unschedulable pod.

    ``candidate_mask`` marks nodes whose filter failure is resolvable by
    removing pods (the engine derives it from recorded reason bits via
    each plugin's ``failure_unresolvable``); None means try every node.
    Candidate search is capped like upstream GetOffsetAndNumCandidates
    (10% of nodes, at least 100)."""
    if not pod_eligible_to_preempt(pod):
        return PreemptionDecision(nominated_node=None, victims=[])
    n = len(nodes)
    want = candidate_count(n)
    candidates: list[Candidate] = []
    pods_list = list(cluster_pods)
    for ni in range(n):
        if candidate_mask is not None and not candidate_mask[ni]:
            continue
        victims = _select_victims_on_node(
            pod, ni, nodes, pods_list, namespaces, volumes, priority_of
        )
        if victims is None:
            continue
        candidates.append(
            Candidate(node_index=ni, node_name=name_of(nodes[ni]), victims=victims)
        )
        if len(candidates) >= want:
            break
    if not candidates:
        return PreemptionDecision(nominated_node=None, victims=[])
    chosen = _pick_one_node(candidates, priority_of)
    return PreemptionDecision(
        nominated_node=chosen.node_name, victims=chosen.victims
    )


def render_postfilter_result(
    failed_nodes: Sequence[str], nominated: str | None
) -> dict[str, dict[str, str]]:
    """The postfilter-result annotation body (store.go:439-456): every
    filtered node gets an entry, the nominated one names the plugin."""
    out: dict[str, dict[str, str]] = {name: {} for name in failed_nodes}
    if nominated is not None:
        out[nominated] = {DEFAULT_PREEMPTION: NOMINATED_MESSAGE}
    return out
