"""Extender webhook proxy + result store.

Mirrors the reference's extender layer (reference
simulator/scheduler/extender/extender.go:100-199, service.go:18-109,
resultstore/resultstore.go:15-198):

- ``HTTPExtender`` POSTs kube-scheduler extender-v1 payloads to the
  user's webhook (urlPrefix + verb) and re-scales prioritize scores by
  ``weight * MaxNodeScore / MaxExtenderPriority`` (extender.go:142-147);
- ``ExtenderService`` dispatches by extender index, recording every
  request/response pair in the result store — the 4 extender annotations
  ``extender-{filter,prioritize,preempt,bind}-result`` hold
  ``{extenderURL: result}`` maps per verb;
- ``override_extenders_cfg_to_simulator`` rewrites an extender config so
  an EXTERNAL scheduler calls the simulator proxy routes
  (``/api/v1/extender/<verb>/<id>``, service.go:88-109); the in-process
  scheduler service calls ``ExtenderService`` directly.

Extender calls are host-side HTTP, deliberately OUTSIDE the jitted
region: when a profile has extenders the scheduler service drops to
per-pod evaluation for exact upstream semantics (filter intersects the
feasible set, prioritize adds to the summed final scores before
selectHost).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Sequence

from ksim_tpu.state.resources import JSON, name_of, namespace_of

logger = logging.getLogger(__name__)

PREFIX = "kube-scheduler-simulator.sigs.k8s.io/"
EXTENDER_FILTER_RESULT_KEY = PREFIX + "extender-filter-result"
EXTENDER_PRIORITIZE_RESULT_KEY = PREFIX + "extender-prioritize-result"
EXTENDER_PREEMPT_RESULT_KEY = PREFIX + "extender-preempt-result"
EXTENDER_BIND_RESULT_KEY = PREFIX + "extender-bind-result"

MAX_EXTENDER_PRIORITY = 10  # extenderv1.MaxExtenderPriority
MAX_NODE_SCORE = 100


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """One configured webhook extender (KubeSchedulerConfiguration
    ``extenders[i]``)."""

    def __init__(self, cfg: JSON) -> None:
        self.url_prefix = (cfg.get("urlPrefix") or "").rstrip("/")
        self.filter_verb = cfg.get("filterVerb") or ""
        self.prioritize_verb = cfg.get("prioritizeVerb") or ""
        self.preempt_verb = cfg.get("preemptVerb") or ""
        self.bind_verb = cfg.get("bindVerb") or ""
        self.weight = int(cfg.get("weight") or 1)
        self.ignorable = bool(cfg.get("ignorable"))
        self.node_cache_capable = bool(cfg.get("nodeCacheCapable"))
        # Resource names this extender manages (extender.go:99-112): with
        # a non-empty set the extender only engages for pods requesting
        # one of them; empty means every pod.
        self.managed_resources = frozenset(
            r.get("name") for r in cfg.get("managedResources") or [] if r.get("name")
        )
        self.timeout = 30.0

    @property
    def name(self) -> str:
        return self.url_prefix  # extender.go Name()

    def is_interested(self, pod: JSON) -> bool:
        """Upstream HTTPExtender.IsInterested: true when managedResources
        is empty, or any container (incl. init containers) requests or
        limits a managed resource (k8s pkg/scheduler/extender.go
        hasManagedResources)."""
        if not self.managed_resources:
            return True
        spec = pod.get("spec") or {}
        for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
            resources = c.get("resources") or {}
            for section in ("requests", "limits"):
                if any(
                    name in self.managed_resources
                    for name in (resources.get(section) or {})
                ):
                    return True
        return False

    def _send(self, verb: str, args: JSON) -> JSON:
        url = f"{self.url_prefix}/{verb}"
        req = urllib.request.Request(
            url,
            data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            if resp.status != 200:
                raise ExtenderError(f"{verb} at {url}: HTTP {resp.status}")
            return json.loads(resp.read())

    def filter(self, args: JSON) -> JSON:
        if not self.filter_verb:
            raise ExtenderError("filterVerb is empty")
        return self._send(self.filter_verb, args)

    def prioritize(self, args: JSON) -> list[JSON]:
        if not self.prioritize_verb:
            raise ExtenderError("prioritizeVerb is empty")
        result = self._send(self.prioritize_verb, args)
        # Re-scale to the scheduler's score range (extender.go:142-147).
        factor = self.weight * (MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY)
        return [
            {**hp, "score": int(hp.get("score") or 0) * factor} for hp in result or []
        ]

    def preempt(self, args: JSON) -> JSON:
        if not self.preempt_verb:
            raise ExtenderError("preemptVerb is empty")
        return self._send(self.preempt_verb, args)

    def bind(self, args: JSON) -> JSON:
        if not self.bind_verb:
            raise ExtenderError("bindVerb is empty")
        return self._send(self.bind_verb, args)


class ExtenderResultStore:
    """Per-pod request/response recording -> the 4 extender annotations
    (resultstore.go:15-198: each annotation is {extenderURL: result}).

    Bounded: entries flush to the pod (scheduler service, or its watch
    loop for proxy-driven external schedulers) and are deleted; the cap
    only guards against callers that never flush."""

    MAX_PODS = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._results: dict[str, dict[str, dict[str, JSON]]] = {}

    @staticmethod
    def _key(pod: JSON) -> str:
        return f"{namespace_of(pod)}/{name_of(pod)}"

    def _add(self, verb: str, pod: JSON, host: str, result: JSON) -> None:
        with self._lock:
            entry = self._results.setdefault(
                self._key(pod), {"filter": {}, "prioritize": {}, "preempt": {}, "bind": {}}
            )
            entry[verb][host] = result
            while len(self._results) > self.MAX_PODS:
                self._results.pop(next(iter(self._results)))

    def add_filter_result(self, args: JSON, result: JSON, host: str) -> None:
        self._add("filter", args.get("pod") or {}, host, result)

    def add_prioritize_result(self, args: JSON, result: JSON, host: str) -> None:
        self._add("prioritize", args.get("pod") or {}, host, result)

    def add_preempt_result(self, args: JSON, result: JSON, host: str) -> None:
        self._add("preempt", args.get("pod") or {}, host, result)

    def add_bind_result(self, args: JSON, result: JSON, host: str) -> None:
        self._add("bind", args.get("pod") or {}, host, result)

    def get_stored_result(self, pod: JSON) -> dict[str, str]:
        """The 4 annotations for one pod (empty maps marshal as "{}")."""
        with self._lock:
            entry = self._results.get(self._key(pod))
            if entry is None:
                return {}
            marshal = lambda o: json.dumps(o, sort_keys=True, separators=(",", ":"))
            return {
                EXTENDER_FILTER_RESULT_KEY: marshal(entry["filter"]),
                EXTENDER_PRIORITIZE_RESULT_KEY: marshal(entry["prioritize"]),
                EXTENDER_PREEMPT_RESULT_KEY: marshal(entry["preempt"]),
                EXTENDER_BIND_RESULT_KEY: marshal(entry["bind"]),
            }

    def delete_data(self, pod: JSON) -> None:
        with self._lock:
            self._results.pop(self._key(pod), None)


class ExtenderService:
    """Index-dispatched proxy with recording (service.go:18-85); the HTTP
    routes /api/v1/extender/<verb>/<id> call straight into this."""

    def __init__(self, extender_cfgs: Sequence[JSON] | None) -> None:
        self.extenders = [HTTPExtender(c) for c in (extender_cfgs or [])]
        self.store = ExtenderResultStore()

    def __bool__(self) -> bool:
        return bool(self.extenders)

    def filter(self, idx: int, args: JSON) -> JSON:
        result = self.extenders[idx].filter(args)
        self.store.add_filter_result(args, result, self.extenders[idx].name)
        return result

    def prioritize(self, idx: int, args: JSON) -> list[JSON]:
        result = self.extenders[idx].prioritize(args)
        self.store.add_prioritize_result(args, result, self.extenders[idx].name)
        return result

    def preempt(self, idx: int, args: JSON) -> JSON:
        result = self.extenders[idx].preempt(args)
        self.store.add_preempt_result(args, result, self.extenders[idx].name)
        return result

    def bind(self, idx: int, args: JSON) -> JSON:
        result = self.extenders[idx].bind(args)
        self.store.add_bind_result(args, result, self.extenders[idx].name)
        return result


def override_extenders_cfg_to_simulator(cfg: JSON, simulator_port: int) -> JSON:
    """Rewrite extender URLs so an external scheduler calls the simulator
    proxy (service.go:88-109)."""
    cfg = dict(cfg)
    extenders = [dict(e) for e in cfg.get("extenders") or []]
    for i, e in enumerate(extenders):
        e["enableHTTPS"] = False
        e.pop("tlsConfig", None)
        e["urlPrefix"] = f"http://localhost:{simulator_port}/api/v1/extender/"
        for verb in ("filterVerb", "prioritizeVerb", "preemptVerb", "bindVerb"):
            if e.get(verb):
                e[verb] = f"{verb[:-4].lower()}/{i}"
        extenders[i] = e
    cfg["extenders"] = extenders
    return cfg
