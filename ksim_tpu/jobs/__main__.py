"""``python -m ksim_tpu.jobs`` — run a fleet worker process.

Thin launcher around :func:`ksim_tpu.jobs.fleet.main`.  Spawning the
worker as the *package* (not ``-m ksim_tpu.jobs.fleet``) avoids the
runpy double-import warning: ``ksim_tpu.jobs.__init__`` imports
``fleet``, so running the submodule as ``__main__`` would execute it a
second time under a different name.
"""

from __future__ import annotations

import sys

from ksim_tpu.jobs.fleet import main

if __name__ == "__main__":
    sys.exit(main())
