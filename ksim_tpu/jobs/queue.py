"""Bounded priority/cost job queue (stdlib-only).

The admission edge of the job plane: ``put`` REFUSES (``JobQueueFull``
-> HTTP 429) instead of blocking — a tenant submitting into a saturated
simulator must get backpressure it can act on, not a hung request
holding an HTTP handler thread.

Ordering (ROADMAP "service round 2: admission by COST"): larger
``priority`` pops first; WITHIN a priority band, shortest-job-first by
``cost`` (the manager passes the spec's event count), ties in
submission order.  Pure priority-then-FIFO — the pre-round-14 behavior
— is the all-default-cost special case.  SJF is what stops a 50k-event
job from convoying every 6k job behind it on a narrow worker pool.

SJF's classic failure is starvation: a steady stream of short jobs
keeps a long one waiting forever.  The bound: every pop that OVERTAKES
an older same-band entry increments that entry's bypass counter, and an
entry bypassed ``max_bypass`` times pops next regardless of cost — so a
job's wait within its band is bounded by ``max_bypass`` pops, by
construction (``KSIM_JOBS_SJF_BYPASS``; the unit tests pin both the
ordering and the bound).

Cancellation of QUEUED jobs is lazy: the manager flips the job's state
and the worker-side ``get`` hands the entry back anyway — the worker
re-checks and skips it (removing from a heap's middle is O(n) and the
entry is dead weight for at most one pop).
"""

from __future__ import annotations

import heapq
import threading
from typing import Any

__all__ = ["JobQueue", "JobQueueFull"]

#: Default starvation bound: a same-band entry is overtaken at most
#: this many times before it pops regardless of cost.
DEFAULT_MAX_BYPASS = 4


class JobQueueFull(Exception):
    """The bounded queue refused a submission (HTTP 429 upstream)."""


class JobQueue:
    """Thread-safe bounded priority+SJF queue with a close() for
    shutdown.  All sizes here are small (the queue is bounded, default
    16), so the O(n) band walks in ``get`` are noise next to the jobs
    themselves."""

    def __init__(self, limit: int, *, max_bypass: "int | None" = None) -> None:
        self.limit = max(int(limit), 0)  # 0 = unbounded
        self.max_bypass = (
            DEFAULT_MAX_BYPASS if max_bypass is None else max(int(max_bypass), 1)
        )
        self._cond = threading.Condition()
        # Heap of (neg_priority, cost, seq, item): priority bands first,
        # cheapest-within-band second, FIFO last.
        self._heap: list[tuple[int, int, int, Any]] = []  # guarded-by: _cond
        self._bypassed: dict[int, int] = {}  # seq -> overtakes; guarded-by: _cond
        self._seq = 0  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self.submitted = 0  # guarded-by: _cond
        self.rejected = 0  # guarded-by: _cond
        self.bypass_pops = 0  # starvation-bound pops; guarded-by: _cond

    def put(self, item: Any, *, priority: int = 0, cost: int = 0) -> None:
        """Enqueue or raise ``JobQueueFull`` — never blocks.  ``cost``
        is the job's size estimate (event count); 0 keeps the legacy
        FIFO position within the band."""
        with self._cond:
            if self._closed:
                raise JobQueueFull("job queue is shut down")
            if self.limit and len(self._heap) >= self.limit:
                self.rejected += 1
                raise JobQueueFull(
                    f"job queue full ({len(self._heap)}/{self.limit})"
                )
            heapq.heappush(self._heap, (-priority, max(int(cost), 0), self._seq, item))
            self._seq += 1
            self.submitted += 1
            self._cond.notify()

    def _pop_locked(self) -> Any:  # ksimlint: lock-held(_cond)
        """SJF-with-starvation-bound pop (see module docstring)."""
        top_band = self._heap[0][0]
        oldest = min(
            (e for e in self._heap if e[0] == top_band), key=lambda e: e[2]
        )
        if (
            oldest is not self._heap[0]
            and self._bypassed.get(oldest[2], 0) >= self.max_bypass
        ):
            chosen = oldest
            self._heap.remove(oldest)
            heapq.heapify(self._heap)
            self.bypass_pops += 1
        else:
            chosen = heapq.heappop(self._heap)
        # Every remaining same-band entry OLDER than the pop was just
        # overtaken once.
        for e in self._heap:
            if e[0] == chosen[0] and e[2] < chosen[2]:
                self._bypassed[e[2]] = self._bypassed.get(e[2], 0) + 1
        self._bypassed.pop(chosen[2], None)
        return chosen[3]

    def get(self, timeout: "float | None" = None) -> Any:
        """Pop the next entry per the admission order; blocks up to
        ``timeout`` (forever when None).  Returns None on timeout or
        once the queue is closed and drained — the worker exit signal."""
        with self._cond:
            while not self._heap:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            return self._pop_locked()

    def close(self) -> None:
        """Refuse new submissions and wake every blocked ``get`` (they
        drain the remaining entries, then return None)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def stats(self) -> dict:
        with self._cond:
            return {
                "depth": len(self._heap),
                "capacity": self.limit,
                "submitted": self.submitted,
                "rejected": self.rejected,
                "bypass_pops": self.bypass_pops,
            }
