"""Bounded priority/FIFO job queue (stdlib-only).

The admission edge of the job plane: ``put`` REFUSES (``JobQueueFull``
-> HTTP 429) instead of blocking — a tenant submitting into a saturated
simulator must get backpressure it can act on, not a hung request
holding an HTTP handler thread.  Ordering is priority-then-FIFO: larger
``priority`` pops first, ties resolve in submission order (a strict
FIFO is the all-default-priority special case).

Cancellation of QUEUED jobs is lazy: the manager flips the job's state
and the worker-side ``get`` hands the entry back anyway — the worker
re-checks and skips it (removing from a heap's middle is O(n) and the
entry is dead weight for at most one pop).
"""

from __future__ import annotations

import heapq
import threading
from typing import Any

__all__ = ["JobQueue", "JobQueueFull"]


class JobQueueFull(Exception):
    """The bounded queue refused a submission (HTTP 429 upstream)."""


class JobQueue:
    """Thread-safe bounded priority queue with a close() for shutdown."""

    def __init__(self, limit: int) -> None:
        self.limit = max(int(limit), 0)  # 0 = unbounded
        self._cond = threading.Condition()
        self._heap: list[tuple[int, int, Any]] = []  # guarded-by: _cond
        self._seq = 0  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self.submitted = 0  # guarded-by: _cond
        self.rejected = 0  # guarded-by: _cond

    def put(self, item: Any, *, priority: int = 0) -> None:
        """Enqueue or raise ``JobQueueFull`` — never blocks."""
        with self._cond:
            if self._closed:
                raise JobQueueFull("job queue is shut down")
            if self.limit and len(self._heap) >= self.limit:
                self.rejected += 1
                raise JobQueueFull(
                    f"job queue full ({len(self._heap)}/{self.limit})"
                )
            heapq.heappush(self._heap, (-priority, self._seq, item))
            self._seq += 1
            self.submitted += 1
            self._cond.notify()

    def get(self, timeout: "float | None" = None) -> Any:
        """Pop the highest-priority (then oldest) entry; blocks up to
        ``timeout`` (forever when None).  Returns None on timeout or
        once the queue is closed and drained — the worker exit signal."""
        with self._cond:
            while not self._heap:
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None
            return heapq.heappop(self._heap)[2]

    def close(self) -> None:
        """Refuse new submissions and wake every blocked ``get`` (they
        drain the remaining entries, then return None)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def stats(self) -> dict:
        with self._cond:
            return {
                "depth": len(self._heap),
                "capacity": self.limit,
                "submitted": self.submitted,
                "rejected": self.rejected,
            }
