"""Multi-worker job fleet: lease-claimed jobs over one shared journal.

ROADMAP "Horizontal scale-out": N worker PROCESSES share one
``KSIM_JOBS_DIR`` behind one HTTP front door.  Every durability enabler
already exists — the checksummed WAL journal is the source of truth
(round 15), segment checkpoints make jobs migratable mid-run
(round 16), and the on-disk AOT executable cache is keyed by
backend+jaxlib so compiled rungs are shareable (rounds 15/17).  This
module adds the one genuinely new mechanism: a LEASE plane that makes
concurrent job claims safe across processes, and the poller that drives
each member's role.

The division of labor (docs/jobs.md "Multi-worker fleet"):

- The FRONT DOOR (``KSIM_WORKERS_ROLE=frontdoor``) owns the HTTP
  surface.  It validates and journals submissions exactly as the solo
  manager does, but runs zero local workers — its registry holds
  MIRROR jobs whose state/result/events are folded back from what the
  worker processes append to the shared journal and to the per-job
  event files (``<dir>/events/<jid>.jsonl``).  SSE fans out from the
  mirror ring, so late joiners replay the recovered backlog gap-free
  across process boundaries, extending the round-16 guarantee.
- Each WORKER (``KSIM_WORKERS_ROLE=worker``) tails the shared journal
  for submits it has not seen, claims one by appending a lease record
  (worker id, epoch, expiry) to ``jobs.leases.jsonl`` under an
  exclusive ``fcntl.flock``, runs it on its local pool (journaling
  state/checkpoint/result records to the SHARED journal exactly like a
  solo manager), renews its leases every heartbeat, and releases them
  only AFTER the terminal record is durable.

Claim safety is the flock: ``LeasePlane.claim`` re-folds the lease
file's current state under the exclusive lock before appending, so two
workers racing for one job serialize and exactly one wins — the loser
sees the winner's unexpired lease and refuses.  Fail-over is lease
EXPIRY: a SIGKILL'd worker stops renewing, its leases age out, and a
surviving worker's claim succeeds with a bumped epoch (``takeover``),
adopts the job from the journal fold, and resumes from the newest valid
checkpoint via the round-16 restore path — counts byte-identical to an
uninterrupted run (the kill-a-worker chaos leg in ``make restart-check``
pins the 6k lock 2524/471).  A RELEASED lease is never re-claimable:
releases happen only after a terminal record is durable, so released ==
finished, and re-running a finished job is the one mistake the protocol
must never make.  The documented residual: a slow-but-ALIVE worker
whose lease expires (e.g. a multi-second GC pause spanning several
missed heartbeats) can race its own successor; heartbeats default to
lease/3, making that window require three consecutive missed renews.

Like journal.py this module is stdlib-only and jax-free at import: the
front door must mirror results in a process whose backend is wedged,
and the worker CLI (``python -m ksim_tpu.jobs.fleet``) defers the
manager import until after argument parsing.

Fault sites ``jobs.lease_claim`` / ``jobs.lease_renew`` (docs/faults.md)
inject I/O errors into the claim/renew paths so chaos runs prove a
failed claim skips ONE poll (another member picks the job up) and
missed renews are survivable until lease expiry.
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading
import time

from ksim_tpu.errors import RunCancelled
from ksim_tpu.faults import FAULTS
from ksim_tpu.jobs.journal import JOURNAL_NAME, _decode_line, _line
from ksim_tpu.obs import (
    TRACE,
    merge_chrome_traces,
    merge_latency_snapshots,
    next_publish_seq,
    process_identity,
    provider_snapshots,
    publish_snapshot,
)

__all__ = [
    "EVENTS_DIR",
    "FileLock",
    "FleetMember",
    "JournalTailer",
    "LEASES_NAME",
    "LeasePlane",
]

logger = logging.getLogger(__name__)

LEASES_NAME = "jobs.leases.jsonl"
EVENTS_DIR = "events"

#: Lease-file compaction bound: renew records accumulate one per owned
#: job per heartbeat, so long fleets would grow the file unboundedly.
_LEASES_MAX_BYTES = 4 * 1024 * 1024

#: Terminal job states, duplicated from ``manager.TERMINAL_STATES`` —
#: this module must stay importable without the manager (and jax-free).
_TERMINAL = frozenset({"succeeded", "failed", "cancelled", "interrupted"})


class FileLock:
    """Cross-process mutual exclusion via ``fcntl.flock`` on a sidecar
    file.  flock is per-open-DESCRIPTION: every ``acquire`` opens a
    fresh descriptor, so two FileLock instances in ONE process exclude
    each other too — which is exactly what the in-process claim-race
    unit tests lean on.  Instances are single-owner (one thread uses
    one instance); cross-thread exclusion is the caller's lock."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fd: "int | None" = None

    def acquire(self, *, blocking: bool = True) -> bool:
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(
                fd, fcntl.LOCK_EX | (0 if blocking else fcntl.LOCK_NB))
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        return True

    def release(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def __enter__(self) -> "FileLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


def _read_recs(path: str) -> list[dict]:
    """Every CRC-valid record from a lease/journal file, stopping at
    the first invalid line (torn tail).  Never raises on a missing
    file — an empty fleet has no lease file yet."""
    recs: list[dict] = []
    try:
        f = open(path, "r", encoding="utf-8", newline="")
    except OSError:
        return recs
    with f:
        for line in f:
            rec = _decode_line(line)
            if rec is None:
                break
            recs.append(rec)
    return recs


class JournalTailer:
    """Incremental reader over an append-only record file: ``poll``
    returns the records appended since the last call, leaving an
    in-flight torn tail (no trailing newline yet) for the next poll.
    A rewrite (compaction replaces the inode, or the file shrank)
    resets the cursor to zero and returns the WHOLE new file with
    ``reset=True`` — the caller's fold must be idempotent, which the
    per-id newest-wins folds here are.  Single-owner: only the fleet
    poller thread touches a tailer."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.offset = 0
        self.invalid = 0
        self._ino: "int | None" = None

    def poll(self) -> "tuple[bool, list[dict]]":
        try:
            st = os.stat(self.path)
        except OSError:
            return False, []
        reset = (self._ino is not None and st.st_ino != self._ino) or (
            st.st_size < self.offset
        )
        if reset:
            self.offset = 0
        self._ino = st.st_ino
        if st.st_size <= self.offset:
            return reset, []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            data = f.read()
        recs: list[dict] = []
        pos = 0
        while True:
            nl = data.find(b"\n", pos)
            if nl < 0:
                break  # torn/in-flight tail: retry next poll
            rec = _decode_line(data[pos:nl + 1].decode("utf-8", "replace"))
            if rec is None:
                self.invalid += 1  # complete but corrupt: skip, count
            else:
                recs.append(rec)
            pos = nl + 1
        self.offset += pos
        return reset, recs


class LeasePlane:
    """The fleet's claim protocol: an append-only, CRC-checksummed
    lease file (``jobs.leases.jsonl``) mutated only under an exclusive
    ``fcntl.flock``.  Record types::

        {"t": "claim",   "id", "worker", "epoch", "expires", "ts",
                         ["takeover", "prev"]}
        {"t": "renew",   "id", "worker", "epoch", "expires", "ts"}
        {"t": "release", "id", "worker", "epoch", "ts"}
        {"t": "counters", "workers": {...}}   (compaction snapshot)

    Folding the file in order yields the current lease per job id
    (newest record wins) plus per-worker counters (claims, takeovers,
    renews, and expired — charged to the worker that LOST the lease).
    Compaction keeps the newest record per id and appends the folded
    counters LAST, so a refold's incremental counting is overwritten by
    the authoritative totals."""

    # The fault/trace planes are leaves under the lease lock (the
    # claim/renew paths consult them while folding under ``_lock``).
    # ksimlint: lock-order(LeasePlane._lock<FaultPlane._lock)
    # ksimlint: lock-order(LeasePlane._lock<TracePlane._lock)

    def __init__(
        self,
        jobs_dir: str,
        *,
        worker: str,
        lease_s: float = 10.0,
        clock=time.time,
    ) -> None:
        self.path = os.path.join(jobs_dir, LEASES_NAME)
        self.worker = worker
        self.lease_s = max(float(lease_s), 0.1)
        self._clock = clock
        self._lock = threading.Lock()
        self._flock = FileLock(f"{self.path}.lock")
        os.makedirs(jobs_dir, exist_ok=True)

    # -- folding ---------------------------------------------------------

    @staticmethod
    def _fold(recs: list[dict]) -> "tuple[dict, dict]":
        """(leases by job id, counters by worker id)."""
        leases: dict[str, dict] = {}
        counters: dict[str, dict] = {}

        def cnt(worker: str) -> dict:
            return counters.setdefault(worker, {
                "claims": 0, "takeovers": 0, "renews": 0, "expired": 0,
            })

        for rec in recs:
            t = rec.get("t")
            if t == "counters":
                counters = {
                    w: dict(c) for w, c in (rec.get("workers") or {}).items()
                }
                continue
            jid, worker = rec.get("id"), rec.get("worker")
            if not isinstance(jid, str) or not isinstance(worker, str):
                continue
            if t == "claim":
                leases[jid] = {
                    "worker": worker,
                    "epoch": int(rec.get("epoch", 1)),
                    "expires": float(rec.get("expires", 0.0)),
                    "released": False,
                    "ts": rec.get("ts"),
                }
                c = cnt(worker)
                c["claims"] += 1
                if rec.get("takeover"):
                    c["takeovers"] += 1
                    prev = rec.get("prev")
                    if isinstance(prev, str):
                        cnt(prev)["expired"] += 1
            elif t == "renew":
                ent = leases.get(jid)
                if ent is not None and ent["worker"] == worker:
                    ent["expires"] = float(rec.get("expires", ent["expires"]))
                    ent["ts"] = rec.get("ts")
                cnt(worker)["renews"] += 1
            elif t == "release":
                ent = leases.get(jid)
                if ent is None:
                    # A compacted file keeps ONLY the release record for
                    # a finished job — reconstruct the tombstone, or the
                    # released-never-reclaimable invariant would not
                    # survive compaction.
                    leases[jid] = {
                        "worker": worker,
                        "epoch": int(rec.get("epoch", 1)),
                        "expires": 0.0,
                        "released": True,
                        "ts": rec.get("ts"),
                    }
                elif ent["worker"] == worker:
                    ent["released"] = True
                    ent["expires"] = 0.0  # no expiry on a tombstone
                    ent["ts"] = rec.get("ts")
        return leases, counters

    def _append_locked(self, recs: list[dict]) -> None:  # ksimlint: lock-held(_lock)
        """Durable batch append; the caller holds ``_lock`` AND the
        flock (the whole point — the fold it just did stays true)."""
        data = "".join(_line(rec) for rec in recs).encode("utf-8")
        fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            view = memoryview(data)
            while view:
                view = view[os.write(fd, view):]
            os.fsync(fd)
        finally:
            os.close(fd)

    # -- the claim protocol ----------------------------------------------

    def claim(self, jid: str) -> "dict | None":
        """Claim ``jid`` for this worker, or refuse (None).  The whole
        read-fold-decide-append runs under the exclusive flock, which
        is what makes two racing claimers serialize.  Refusals: a live
        lease held by another worker, or a RELEASED lease (released ==
        the owner journaled a terminal record; re-claiming would re-run
        a finished job).  An expired unreleased lease is the fail-over
        case: the claim succeeds with a bumped epoch and is counted as
        a takeover against the previous owner."""
        with TRACE.span("jobs.lease_claim", job=jid, worker=self.worker):
            with self._lock:
                FAULTS.check("jobs.lease_claim")
                with self._flock:
                    leases, _ = self._fold(_read_recs(self.path))
                    now = self._clock()
                    ent = leases.get(jid)
                    takeover = False
                    prev: "str | None" = None
                    if ent is not None:
                        if ent["released"]:
                            return None
                        if ent["worker"] == self.worker and ent["expires"] > now:
                            return dict(ent)  # idempotent re-claim
                        if ent["expires"] > now:
                            return None  # live lease, someone else's
                        takeover = True
                        prev = ent["worker"]
                    epoch = (ent["epoch"] + 1) if ent is not None else 1
                    rec: dict = {
                        "t": "claim", "id": jid, "worker": self.worker,
                        "epoch": epoch, "expires": now + self.lease_s,
                        "ts": round(now, 3),
                    }
                    if takeover:
                        rec["takeover"] = True
                        rec["prev"] = prev
                    self._append_locked([rec])
            if takeover:
                TRACE.event(
                    "jobs.lease_expired", job=jid, worker=prev,
                    epoch=epoch - 1,
                )
            TRACE.event(
                "jobs.fleet_claim", job=jid, worker=self.worker,
                epoch=epoch, takeover=takeover,
            )
            return {
                "worker": self.worker, "epoch": epoch,
                "expires": now + self.lease_s, "released": False,
                "ts": rec["ts"],
            }

    def renew(self, jids: list[str]) -> int:
        """Heartbeat: extend this worker's live leases on ``jids``.
        Returns how many renewed (a lease that expired and was taken
        over in the meantime is NOT renewed — the job is no longer
        ours, and the local runner's next cancel check should stop
        it)."""
        if not jids:
            return 0
        with TRACE.span("jobs.lease_renew", worker=self.worker, n=len(jids)):
            with self._lock:
                FAULTS.check("jobs.lease_renew")
                with self._flock:
                    leases, _ = self._fold(_read_recs(self.path))
                    now = self._clock()
                    recs = []
                    for jid in jids:
                        ent = leases.get(jid)
                        if (
                            ent is None
                            or ent["released"]
                            or ent["worker"] != self.worker
                        ):
                            continue
                        recs.append({
                            "t": "renew", "id": jid, "worker": self.worker,
                            "epoch": ent["epoch"],
                            "expires": now + self.lease_s,
                            "ts": round(now, 3),
                        })
                    if recs:
                        self._append_locked(recs)
                    return len(recs)

    def release(self, jid: str) -> None:
        """Mark this worker's lease finished — append-only, AFTER the
        job's terminal record is durable in the shared journal (the
        released-means-finished invariant ``claim`` relies on)."""
        with self._lock:
            with self._flock:
                leases, _ = self._fold(_read_recs(self.path))
                ent = leases.get(jid)
                if ent is None or ent["worker"] != self.worker:
                    return
                self._append_locked([{
                    "t": "release", "id": jid, "worker": self.worker,
                    "epoch": ent["epoch"], "ts": round(self._clock(), 3),
                }])

    # -- views & compaction ----------------------------------------------

    def leases(self) -> dict:
        with self._lock:
            with self._flock:
                leases, _ = self._fold(_read_recs(self.path))
                return leases

    def counters(self) -> dict:
        with self._lock:
            with self._flock:
                _, counters = self._fold(_read_recs(self.path))
                return counters

    def maybe_compact(self, *, max_bytes: int = _LEASES_MAX_BYTES) -> bool:
        """Rewrite the lease file as newest-record-per-id plus the
        folded counters (LAST, so a refold's incremental counts are
        overwritten by the authoritative totals).  Non-blocking flock:
        contention means another member is mid-claim — skip."""
        with self._lock:
            try:
                if os.path.getsize(self.path) <= max_bytes:
                    return False
            except OSError:
                return False
            if not self._flock.acquire(blocking=False):
                return False
            try:
                recs = _read_recs(self.path)
                leases, counters = self._fold(recs)
                now = self._clock()
                out = []
                for jid, ent in leases.items():
                    if ent["released"]:
                        out.append({
                            "t": "release", "id": jid,
                            "worker": ent["worker"], "epoch": ent["epoch"],
                            "ts": ent["ts"],
                        })
                    else:
                        out.append({
                            "t": "claim", "id": jid, "worker": ent["worker"],
                            "epoch": ent["epoch"], "expires": ent["expires"],
                            "ts": ent["ts"] or round(now, 3),
                        })
                out.append({"t": "counters", "workers": counters})
                lines = [_line(rec) for rec in out]
                tmp = f"{self.path}.tmp{os.getpid()}"
                try:
                    with open(tmp, "w", encoding="utf-8") as f:
                        f.writelines(lines)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, self.path)
                except OSError:
                    return False
                return True
            finally:
                self._flock.release()


class FleetMember:
    """One process's seat in the fleet: a single daemon poller thread
    driving the role's duties against the shared ``KSIM_JOBS_DIR``.

    Worker: tail the shared journal, claim unleased (or expired-lease)
    submits, adopt them onto the local pool, renew leases every
    heartbeat, forward the owned jobs' event rings to the per-job event
    files, honor journaled cancel records, and release leases once the
    terminal record is durable.

    Front door: tail the shared journal and mirror worker-journaled
    state/result records into the local mirror jobs (quietly — the
    event FILES are the event authority, the journal the state
    authority), tail the event files into the mirror SSE rings, and
    fold lease ownership into each job's status fields.  After a
    takeover a mirror's progress can legitimately drop back to the
    checkpoint baseline the new owner resumed from — truthful, not a
    bug (docs/jobs.md)."""

    # Deliberately lock-poor: ``_lock`` guards only the member's own
    # dicts and is never held across calls into the manager or a job —
    # the poller snapshots under it, then works outside it.

    def __init__(
        self,
        manager,
        jobs_dir: str,
        *,
        role: str,
        worker_id: str,
        lease_s: float = 10.0,
        heartbeat_s: "float | None" = None,
        poll_s: float = 0.5,
        publish_s: "float | None" = None,
    ) -> None:
        if role not in ("frontdoor", "worker"):
            raise ValueError(f"unknown fleet role {role!r}")
        self._manager = manager
        self._dir = jobs_dir
        self.role = role
        self.worker_id = worker_id
        self.lease_s = max(float(lease_s), 0.1)
        self.heartbeat_s = (
            max(float(heartbeat_s), 0.05)
            if heartbeat_s is not None
            else self.lease_s / 3.0
        )
        self.poll_s = max(float(poll_s), 0.02)
        # Telemetry publish cadence (docs/observability.md "Fleet
        # observability"): KSIM_OBS_PUBLISH_S seconds between snapshot
        # publishes, default 10; 0 disables the publisher thread
        # entirely (and the obs/ directory is never created).
        if publish_s is None:
            raw = os.environ.get("KSIM_OBS_PUBLISH_S", "")
            try:
                publish_s = float(raw) if raw else 10.0
            except ValueError:
                publish_s = 10.0
        self.publish_s = max(float(publish_s), 0.0)
        self.plane = LeasePlane(jobs_dir, worker=worker_id, lease_s=lease_s)
        self._tailer = JournalTailer(os.path.join(jobs_dir, JOURNAL_NAME))
        self._events_dir = os.path.join(jobs_dir, EVENTS_DIR)
        os.makedirs(self._events_dir, exist_ok=True)
        # Poller-thread-only working state (no cross-thread readers).
        self._folded: dict[str, dict] = {}
        self._drained: dict[str, int] = {}
        self._event_tailers: dict[str, JournalTailer] = {}
        self._done: set[str] = set()
        self._last_renew = 0.0
        # Cross-thread-visible state (snapshot() runs on HTTP threads).
        self._lock = threading.Lock()
        self._owned: dict[str, object] = {}  # guarded-by: _lock
        self._polls = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._publish_thread: "threading.Thread | None" = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        t = threading.Thread(
            target=self._poll_loop,
            name=f"fleet-{self.role}-{self.worker_id}",
            daemon=True,
        )  # ksimlint: thread-role(fleet-poller)
        t.start()
        self._thread = t
        if self.publish_s > 0:
            p = threading.Thread(
                target=self._publish_loop,
                name=f"obs-publish-{self.worker_id}",
                daemon=True,
            )  # ksimlint: thread-role(obs-publisher)
            p.start()
            self._publish_thread = p

    def stop(self, timeout: "float | None" = 5.0) -> None:
        """Stop the poller, then run ONE final poll inline to drain any
        remaining owned-job events and release leases of jobs that
        reached a terminal state during shutdown (a lease left behind
        simply expires — correctness never depends on this drain).
        With publishing on, one final snapshot publishes AFTER the
        drain, so the on-disk telemetry reflects this member's terminal
        truth."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        p = self._publish_thread
        if p is not None:
            p.join(timeout)
        try:
            self._poll_once()
        except Exception:
            logger.exception("fleet final drain failed")
        if self.publish_s > 0:
            try:
                self.publish_once()
            except Exception:
                logger.exception("final obs publish failed")

    # -- the poller ------------------------------------------------------

    def _poll_loop(self) -> None:  # ksimlint: thread-role(fleet-poller)
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_once()
            except RunCancelled:
                raise
            except Exception:
                # Containment: one bad poll (an armed lease fault, a
                # transient I/O error) must not kill the member — the
                # next tick retries from durable state.
                logger.exception(
                    "fleet poll failed (role=%s worker=%s)",
                    self.role, self.worker_id,
                )

    def _poll_once(self) -> None:
        reset, recs = self._tailer.poll()
        if reset:
            self._folded.clear()
        self._fold(recs)
        if self.role == "worker":
            self._poll_worker()
        else:
            self._poll_frontdoor()
        self.plane.maybe_compact()
        with self._lock:
            self._polls += 1

    def _fold(self, recs: list[dict]) -> None:
        """Incremental journal fold, mirroring ``JobManager._recover``'s
        shapes so worker adoption can hand the entry straight to
        ``JobManager.adopt``.  The front door drops checkpoint PAYLOADS
        (multi-MB store snapshots it will never restore), keeping only
        the segment number for status; workers keep the newest two
        (newest first to try, one fallback behind it)."""
        for rec in recs:
            t, jid = rec.get("t"), rec.get("id")
            if not isinstance(jid, str):
                continue
            ent = self._folded.setdefault(jid, {
                "submit": None, "state": None, "error": None,
                "result": None, "cancel": False,
                "started": None, "finished": None,
                "checkpoints": [], "history": [],
                "checkpoint_segment": None,
            })
            if t == "submit":
                ent["submit"] = rec
            elif t == "state":
                state = rec.get("state")
                ent["state"], ent["error"] = state, rec.get("error")
                if state == "running":
                    ent["started"] = rec.get("ts")
                elif state in _TERMINAL:
                    ent["finished"] = rec.get("ts")
                ent["history"].append({
                    "state": state, "ts": rec.get("ts"),
                    "error": rec.get("error"),
                })
            elif t == "result":
                ent["result"] = rec.get("result")
            elif t == "cancel":
                ent["cancel"] = True
            elif t == "checkpoint":
                ent["checkpoint_segment"] = rec.get("segment")
                if self.role == "worker":
                    ent["checkpoints"] = (ent["checkpoints"] + [rec])[-2:]

    # -- worker role -----------------------------------------------------

    def _poll_worker(self) -> None:
        self._adopt_claimable()
        self._apply_cancels()
        now = time.monotonic()
        if now - self._last_renew >= self.heartbeat_s:
            with self._lock:
                owned = list(self._owned)
            try:
                self.plane.renew(owned)
            except Exception:
                # A missed renew (armed jobs.lease_renew fault, I/O
                # blip) is survivable until lease expiry.
                logger.exception("lease renew failed (worker=%s)",
                                 self.worker_id)
            self._last_renew = now
        self._drain_owned()

    def _adopt_claimable(self) -> None:
        stats = self._manager.queue.stats()
        if stats["capacity"] and stats["depth"] >= stats["capacity"]:
            return  # local backpressure: let another member claim
        leases = None
        now = time.time()
        for jid in sorted(self._folded):
            ent = self._folded[jid]
            if (
                ent["submit"] is None
                or ent["state"] in _TERMINAL
                or jid in self._done
            ):
                continue
            with self._lock:
                if jid in self._owned:
                    continue
            if leases is None:
                leases = self.plane.leases()  # one read per poll
            lease = leases.get(jid)
            if lease is not None and (
                lease["released"]
                or (lease["worker"] != self.worker_id
                    and lease["expires"] > now)
            ):
                continue  # finished, or someone else holds it live
            try:
                won = self.plane.claim(jid)
                if won is None:
                    continue  # lost the race under the flock
            except Exception:
                logger.exception("lease claim failed (job=%s)", jid)
                continue
            try:
                job = self._manager.adopt(jid, ent, won)
            except Exception:
                # Local backpressure (JobQueueFull) or a transient
                # build failure: KEEP the lease and retry next poll —
                # claim() is idempotent for our own live lease, and an
                # un-renewed lease simply expires back to the fleet.
                logger.exception("adopt failed (job=%s); retrying", jid)
                continue
            if job is None:
                # The spec no longer parses; adopt journaled the
                # terminal refusal, so the lease lifecycle ends too.
                self.plane.release(jid)
                self._done.add(jid)
                continue
            with self._lock:
                self._owned[jid] = job
            self._drained.setdefault(jid, 0)

    def _apply_cancels(self) -> None:
        with self._lock:
            owned = dict(self._owned)
        for jid, job in owned.items():
            ent = self._folded.get(jid)
            if ent is not None and ent["cancel"] and not job.cancel.is_set():
                job.request_cancel()

    def _drain_owned(self) -> None:
        with self._lock:
            owned = dict(self._owned)
        for jid, job in owned.items():
            evs, nxt, done = job.events_since(self._drained.get(jid, 0), 0)
            self._drained[jid] = nxt
            out = [ev for ev in evs if not ev.get("recovered")]
            if out:
                try:
                    self._append_events(jid, out)
                except OSError:
                    # Events are best-effort streaming evidence; the
                    # journal carries the authoritative state.
                    logger.exception("event append failed (job=%s)", jid)
            if done:
                try:
                    self.plane.release(jid)
                except Exception:
                    logger.exception("lease release failed (job=%s)", jid)
                with self._lock:
                    self._owned.pop(jid, None)
                self._done.add(jid)

    def _event_path(self, jid: str) -> str:
        return os.path.join(self._events_dir, f"{jid}.jsonl")

    def _append_events(self, jid: str, evs: list[dict]) -> None:
        """Forward a batch of the owned job's ring events to its event
        file — single O_APPEND write, record-atomic against a deposed
        predecessor's last gasp."""
        data = "".join(
            _line({"t": "event", "id": jid, "ev": ev}) for ev in evs
        ).encode("utf-8")
        fd = os.open(
            self._event_path(jid),
            os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644,
        )
        try:
            view = memoryview(data)
            while view:
                view = view[os.write(fd, view):]
        finally:
            os.close(fd)

    # -- front-door role -------------------------------------------------

    def _poll_frontdoor(self) -> None:
        try:
            leases = self.plane.leases()
        except Exception:
            logger.exception("lease read failed (frontdoor)")
            leases = {}
        for jid, ent in self._folded.items():
            job = self._manager.get(jid)
            if job is None:
                self._event_tailers.pop(jid, None)
                continue
            tailer = self._event_tailers.get(jid)
            if tailer is None:
                tailer = self._event_tailers[jid] = JournalTailer(
                    self._event_path(jid))
            _, evrecs = tailer.poll()
            for rec in evrecs:
                ev = rec.get("ev")
                if isinstance(ev, dict):
                    job.emit(dict(ev), vital=ev.get("event") in (
                        "state", "progress"))
            lease = leases.get(jid)
            if lease is not None:
                job._set_lease(lease)
            if ent["state"] is not None:
                job._mirror_state(
                    ent["state"], error=ent["error"], result=ent["result"],
                    started=ent["started"], finished=ent["finished"],
                    segment=ent["checkpoint_segment"],
                )

    # -- telemetry publishing (docs/observability.md) --------------------

    def _publish_loop(self) -> None:  # ksimlint: thread-role(obs-publisher)
        while not self._stop.wait(self.publish_s):
            try:
                self.publish_once()
            except RunCancelled:
                raise
            except Exception:
                # Containment: telemetry is evidence, never load-bearing
                # — a failed publish leaves the previous snapshot
                # standing and the next tick retries.
                logger.exception(
                    "obs publish failed (role=%s worker=%s)",
                    self.role, self.worker_id,
                )

    def _obs_document(self) -> "tuple[dict, dict]":
        """(snapshot document, merged Chrome trace document) for this
        member.  Job spans (``jobs.run``, ``replay.dispatch``, ...)
        land on each job's PRIVATE plane via the worker's scoped
        override, so the global ``TRACE`` alone under-reports a worker:
        both documents merge the global plane with every registered
        job's plane — histograms bucket-wise exactly (fixed edges),
        rings as one process lane."""
        now = time.time()
        ident = process_identity(role=self.role, worker_id=self.worker_id)
        ident["seq"] = next_publish_seq()
        ident["published_at"] = round(now, 3)
        ident["publish_s"] = self.publish_s
        jobs = self._manager.jobs()
        sections = [TRACE.snapshot()]
        traces = {self.worker_id: TRACE.export_chrome()}
        for job in jobs:
            plane = getattr(job, "trace", None)
            if plane is None:
                continue
            sections.append(plane.snapshot())
            traces[f"{self.worker_id}:{job.id}"] = plane.export_chrome()
        events: dict[str, int] = {}
        hist_snaps: dict[str, list] = {}
        for sec in sections:
            for name, v in (sec.get("events") or {}).items():
                events[name] = events.get(name, 0) + int(v)
            for name, snap in (sec.get("histograms") or {}).items():
                hist_snaps.setdefault(name, []).append(snap)
        histograms = {
            n: merge_latency_snapshots(snaps)
            for n, snaps in sorted(hist_snaps.items())
        }
        trace_sec = {
            "enabled": sections[0].get("enabled", False),
            "ring": sections[0].get("ring") or {},
            "histograms": histograms,
            "events": dict(sorted(events.items())),
        }
        try:
            mine = self.plane.counters().get(self.worker_id) or {}
        except Exception:
            mine = {}
        doc: dict = {
            "process": ident,
            # This member's own lease-protocol counters — numeric, so
            # the fleet merge's counter SUM is meaningful across
            # workers (each publishes only its own row).
            "counters": {f"fleet_{k}": v for k, v in sorted(mine.items())},
            "timings": {},
            "trace": trace_sec,
            "phase_totals": {
                n: [s["total_seconds"], s["count"]]
                for n, s in histograms.items()
                if s.get("count")
            },
            "faults": FAULTS.snapshot(),
            "jobs": self._manager.snapshot(),
        }
        for name, snap in provider_snapshots().items():
            doc.setdefault(name, snap)
        trace_doc = merge_chrome_traces(traces)
        # Pin this process's lane name to the WORKER id.  The merge
        # names a lane after the first keyed export that contributed an
        # event on that pid; if the global ring happens to be empty at
        # publish time a per-job key ("w1:job-0001") would win — or no
        # lane would exist at all — and the fleet-level merge downstream
        # would lose the one-lane-per-worker invariant trace_check
        # run 5 asserts.
        pid = os.getpid()
        for ev in trace_doc["traceEvents"]:
            if (
                ev.get("ph") == "M"
                and ev.get("name") == "process_name"
                and ev.get("pid") == pid
            ):
                ev["args"] = {"name": self.worker_id}
                break
        else:
            trace_doc["traceEvents"].insert(0, {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": self.worker_id},
            })
        return doc, trace_doc

    def publish_once(self) -> str:
        """Build and crash-atomically publish this member's telemetry
        snapshot + merged trace export to ``<jobs_dir>/obs/``."""
        doc, trace_doc = self._obs_document()
        return publish_snapshot(
            self._dir, doc, worker_id=self.worker_id, trace_doc=trace_doc
        )

    # -- evidence --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            owned = sorted(self._owned)
            polls = self._polls
        try:
            workers = self.plane.counters()
        except Exception:
            workers = {}
        return {
            "role": self.role,
            "worker_id": self.worker_id,
            "lease_s": self.lease_s,
            "heartbeat_s": self.heartbeat_s,
            "owned": owned,
            "polls": polls,
            "journal_invalid": self._tailer.invalid,
            "workers": workers,
        }


def main(argv: "list[str] | None" = None) -> int:
    """Worker-process entry point: ``python -m ksim_tpu.jobs.fleet
    --dir <KSIM_JOBS_DIR> [--worker-id w1] [--workers 2]``.  Builds a
    worker-role JobManager (which starts the fleet poller), prints
    ``READY <worker id>`` for the spawning test/bench harness, and
    parks until SIGTERM/SIGINT."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(description="ksim-tpu fleet worker")
    parser.add_argument("--dir", required=True, help="shared KSIM_JOBS_DIR")
    parser.add_argument("--worker-id", default=f"w{os.getpid()}")
    parser.add_argument("--workers", type=int, default=None,
                        help="local pool size (default KSIM_JOBS_WORKERS)")
    args = parser.parse_args(argv)

    from ksim_tpu.jobs.manager import JobManager
    from ksim_tpu.util import enable_compilation_cache

    # A worker is a product entrypoint: arm the persistent XLA compile
    # cache (KSIM_COMPILE_CACHE) like the simulator/scheduler CLIs do,
    # so a fleet pointed at one cache dir compiles each rung once
    # fleet-wide instead of once per process.
    enable_compilation_cache()
    jm = JobManager(
        workers=args.workers,
        jobs_dir=args.dir,
        role="worker",
        worker_id=args.worker_id,
    )
    mode = os.environ.get("KSIM_AOT_PREWARM")
    if mode in ("1", "2"):
        # The fleet is where mode 2 earns its keep: workers sharing one
        # KSIM_AOT_CACHE speculatively load each other's compiles, so
        # one worker's cold start is every worker's warm start
        # (engine/replay.py prewarm_rescan_loop; cmd/simulator.py runs
        # the same thread for the solo server).
        from ksim_tpu.engine.replay import prewarm_aot_cache, prewarm_rescan_loop

        threading.Thread(
            target=prewarm_rescan_loop if mode == "2" else prewarm_aot_cache,
            name="aot-prewarm",
            daemon=True,
        ).start()
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    print(f"READY {args.worker_id}", flush=True)
    stop.wait()
    jm.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
