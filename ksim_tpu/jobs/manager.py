"""Tenant job plane: a bounded queue + worker pool over ScenarioRunner.

Simulation-as-a-service (ROADMAP "concurrent replays behind the API
server"): N tenants submit scenario jobs concurrently, a fixed worker
pool keeps the hardware hot, and every job runs in full isolation —

- its own ``ClusterStore`` + ``SchedulerService`` + ``ScenarioRunner``
  (built from the job's inline spec; tenant specs may NOT reference
  server files or import plugin modules),
- its own **TracePlane** (private ring + latency histograms, every
  record tagged ``job=<id>``), installed for the worker thread via the
  global plane's scoped override (``obs.TracePlane.scoped``) — no call
  site anywhere in the pipeline changes,
- its own **FaultPlane** (``KSIM_JOBS_FAULTS``), checked next to the
  process-global one at ``jobs.run`` and the replay sites, so a chaos
  schedule degrades ONE tenant while its neighbors' counts stay locked,
- a cooperative **cancel** flag the runner honors between steps and
  INSIDE the segment reconcile (a mid-segment cancel rolls the
  in-flight store transaction back — the job's store stays consistent).

What jobs share is exactly what SHOULD be shared: the process-wide
compiled-executable cache (engine/compilecache.py) — two tenants on the
same bucketed shape rung compile once — and the worker pool itself.

The HTTP surface lives in server/http.py (``/api/v1/jobs``): submit /
status / result / cancel plus an SSE stream of the job's progress and
trace events, fed by the job plane's record sink.

Admission (ROADMAP "service round 2", round 14) is cost- and
bounds-aware:

- the queue orders shortest-job-first within a priority band, costed by
  the spec's event count, with a starvation bound
  (``KSIM_JOBS_SJF_BYPASS`` — jobs/queue.py), so a 50k-event job cannot
  convoy 6k jobs behind it;
- per-submission resource bounds (``KSIM_JOBS_MAX_EVENTS`` /
  ``KSIM_JOBS_MAX_NODES``) refuse oversized specs at POST time with
  ``JobLimitExceeded`` (HTTP 413) — measured against what the job would
  actually replay.  Trace-sourced specs are refused DURING streaming
  ingest (``TraceBoundExceeded`` from traces/resample.py's monotone
  lower bound): the server stops reading the trace at the first proof
  of excess instead of compiling the whole stream first;
- scenarios may reference REGISTERED traces by name
  (``spec.scenario.source.trace.name`` resolved in the operator's
  ``KSIM_TRACES_DIR`` — ksim_tpu/traces/registry.py); raw ``path``
  references are refused exactly like the snapshot-path fields;
- a spec may arm its own chaos (``spec.faults`` —
  scenario/spec.py ``faults_spec_from_doc``) on the job's PRIVATE
  fault plane, sites restricted to ``JOB_FAULT_SITES`` like the
  operator's ``KSIM_JOBS_FAULTS`` ordinals.

Durability (ROADMAP round 15): when ``KSIM_JOBS_DIR`` is set, every
submission, state transition, cancellation and result document is
journaled through the crash-safe WAL in ksim_tpu/jobs/journal.py
BEFORE the in-memory state machine observes it, and a restarted
manager replays that journal to reconstruct the registry — completed
results serve byte-identically, jobs that died mid-run surface as
``interrupted`` (or re-enqueue under ``KSIM_JOBS_RESUME=1``).  Unset,
the plane is exactly the in-memory-only plane of rounds 13–14.

Incremental resume (round 16, docs/jobs.md "Incremental resume"): a
solo device-replay job also journals SEGMENT CHECKPOINTS — every
``KSIM_JOBS_CHECKPOINT_EVERY`` committed segment reconciles, one
``checkpoint`` record carries the exact store state
(``ClusterStore.checkpoint``), the event-stream cursor, the service's
determinism carries (pass counter, backoff map, featurizer slot order,
pnts rotation) and the partial result accounting.  Under
``KSIM_JOBS_RESUME=1`` the worker restores from the NEWEST valid
checkpoint and replays only the remaining suffix, byte-identical to an
uninterrupted run; an unusable checkpoint falls back to the previous
one, then scratch.  Checkpoints are best-effort by policy: a
non-restorable moment (Permit-waiting pods), an oversized snapshot
(``KSIM_JOBS_CHECKPOINT_MAX_BYTES``) or an append failure SKIPS the
checkpoint with a counted ``jobs.checkpoint`` event — never fails the
job.

Tenancy (round 16, ROADMAP service round 4 (c)): submissions carry a
tenant label (HTTP ``X-Ksim-Tenant`` or ``spec.tenant``; default
``default``) and the operator may bound each tenant's concurrency
(``KSIM_JOBS_TENANT_MAX_ACTIVE``) and sustained submission rate
(``KSIM_JOBS_TENANT_RATE``, a token bucket) — over either bound the
submit raises ``JobThrottled`` (HTTP 429 with a ``Retry-After`` hint).

Environment (docs/env.md "Job plane"): ``KSIM_JOBS_WORKERS``,
``KSIM_JOBS_QUEUE``, ``KSIM_JOBS_RING``, ``KSIM_JOBS_KEEP``,
``KSIM_JOBS_EVENTS``, ``KSIM_JOBS_FAULTS``, ``KSIM_JOBS_MAX_EVENTS``,
``KSIM_JOBS_MAX_NODES``, ``KSIM_JOBS_SJF_BYPASS``,
``KSIM_JOBS_TENANT_MAX_ACTIVE``, ``KSIM_JOBS_TENANT_RATE``;
durability: ``KSIM_JOBS_DIR``, ``KSIM_JOBS_RESUME``,
``KSIM_JOBS_JOURNAL_MAX_BYTES``, ``KSIM_JOBS_CHECKPOINT_EVERY``,
``KSIM_JOBS_CHECKPOINT_MAX_BYTES``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Any

from ksim_tpu.errors import RunCancelled
from ksim_tpu.faults import FAULTS, FaultPlane
from ksim_tpu.jobs.journal import JOURNAL_NAME, JobJournal
from ksim_tpu.jobs.queue import JobQueue, JobQueueFull
from ksim_tpu.obs import TRACE, TracePlane

logger = logging.getLogger(__name__)

__all__ = [
    "Job",
    "JobLimitExceeded",
    "JobManager",
    "JobQueueFull",
    "JobThrottled",
    "parse_job_faults",
]


class JobLimitExceeded(Exception):
    """A submission exceeded the operator's per-job resource bounds
    (``KSIM_JOBS_MAX_EVENTS`` / ``KSIM_JOBS_MAX_NODES``) — HTTP 413
    upstream, with this message as the reason body."""


class JobThrottled(Exception):
    """A tenant is over its admission bound — the concurrency quota
    (``KSIM_JOBS_TENANT_MAX_ACTIVE``) or the submission-rate token
    bucket (``KSIM_JOBS_TENANT_RATE``).  HTTP 429 upstream with
    ``retry_after`` (seconds) as the ``Retry-After`` header: the bucket
    knows exactly when the next token lands, so the hint is a real
    schedule, not a guess."""

    def __init__(self, msg: str, *, retry_after: float) -> None:
        super().__init__(msg)
        self.retry_after = retry_after

#: Final job states (no transitions out).  ``interrupted`` is
#: recovery-only: the journal saw the job queued/running when the
#: process died (docs/jobs.md "Durability & recovery") — terminal
#: unless ``KSIM_JOBS_RESUME=1`` re-enqueues it as a fresh run.
TERMINAL_STATES = frozenset({"succeeded", "failed", "cancelled", "interrupted"})

#: Sites a tenant-job private plane may arm.  The private plane is only
#: CHECKED at these (jobs/manager.py + the runner/driver's lane-plane
#: checks); accepting any other site would arm a schedule that can
#: never fire — the vacuously-green chaos run every parser in this
#: repo refuses.
JOB_FAULT_SITES = frozenset(
    {"jobs.run", "replay.lower", "replay.dispatch", "replay.reconcile"}
)


def _job_fault_specs(spec: str) -> dict[int, list[str]]:
    """Parse ``KSIM_JOBS_FAULTS`` into per-ordinal schedule SPEC
    strings (the manager builds a FRESH plane per submission from
    these, so a refused submission can never leave schedules behind on
    a shared plane).

    Syntax mirrors ``KSIM_FLEET_FAULTS``: comma/semicolon-separated
    ``<ordinal>:<site>=<schedule>[@error]`` entries where ``ordinal``
    is the job's 0-based SUBMISSION index — e.g.
    ``"0:replay.dispatch=always@device"`` arms only the first job
    submitted.  Sites outside ``JOB_FAULT_SITES`` and malformed entries
    raise."""
    specs: dict[int, list[str]] = {}
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        ord_s, sep, rest = part.partition(":")
        if not sep or not ord_s.strip().isdigit():
            raise ValueError(
                f"KSIM_JOBS_FAULTS entry {part!r}: expected "
                f"<job-ordinal>:<site>=<schedule>"
            )
        site = rest.partition("=")[0].strip()
        if site not in JOB_FAULT_SITES:
            raise ValueError(
                f"KSIM_JOBS_FAULTS entry {part!r}: site {site!r} is not a "
                f"job-plane site (have {sorted(JOB_FAULT_SITES)})"
            )
        # Fail-fast on the SCHEDULE too (a throwaway plane): an operator
        # typo must raise at JobManager construction, not surface later
        # as an HTTP 400 blaming some tenant's spec.faults while the
        # chaos schedule silently never runs.
        FaultPlane().configure(rest)
        specs.setdefault(int(ord_s), []).append(rest)
    return specs


def parse_job_faults(spec: str) -> dict[int, FaultPlane]:
    """``KSIM_JOBS_FAULTS`` -> per-job-ordinal fault planes (see
    ``_job_fault_specs`` for the grammar and refusals)."""
    planes: dict[int, FaultPlane] = {}
    for ordinal, entries in _job_fault_specs(spec).items():
        plane = planes[ordinal] = FaultPlane()
        for entry in entries:
            plane.configure(entry)
    return planes


def _tenant_trace_resolver(trace_doc: dict) -> str:
    """The job plane's trace resolver: registered names only.  A raw
    ``path`` is refused for the same reason ``initialSnapshotPath`` is —
    tenants must never make the server read arbitrary files; the
    operator registers traces by placing them in ``KSIM_TRACES_DIR``."""
    from ksim_tpu.scenario.spec import ScenarioSpecError, default_trace_resolver

    if trace_doc.get("path"):
        raise ScenarioSpecError(
            "source.trace.path is not allowed in a tenant job spec — "
            "reference a trace registered in KSIM_TRACES_DIR by name"
        )
    return default_trace_resolver(trace_doc)


def _spec_hash(sim: dict) -> str:
    """Canonical content hash of a job's simulator spec (round 19; the
    doc half shipped in round 17 — docs/jobs.md "Resume across a config
    change").  Checkpoint records carry it so ``_restore_checkpoint``
    can REFUSE a restore whose spec no longer matches the resubmitted
    job: the rebuilt SchedulerService would silently diverge from the
    carries the old config produced.  Sorted-key compact JSON makes the
    hash independent of dict ordering; the 16-hex truncation (64 bits)
    is plenty for an equality check that only ever compares a job
    against its own history."""
    blob = json.dumps(
        sim or {}, sort_keys=True, separators=(",", ":"), default=str
    )
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _parse_job_spec(
    doc: Any, *, event_bound: int = 0, node_bound: int = 0
) -> tuple[list, dict, int, str]:
    """Validate a tenant job document -> (operations, simulator spec,
    priority, canonical fault spec).  Accepts the
    SchedulerSimulation-ish shape::

        {"spec": {"priority": 0,
                  "simulator": {...},          # recordMode/preemption/
                                               # deviceReplay/fleet/
                                               # schedulerConfig/
                                               # initialSnapshot (INLINE)
                  "faults": {...},             # site -> schedule (the
                                               # job's PRIVATE plane)
                  "scenario": {"operations": [...]   # or source.trace
                  }}}

    or a bare ``{"operations": [...]}``.  File-path fields are REFUSED:
    tenants must not make the server read its own filesystem (the
    KEP-184 mounted-file workflow is the operator's
    ``cmd/simulation.py``, not this surface); trace references resolve
    by REGISTERED NAME only (``_tenant_trace_resolver``).

    ``event_bound`` / ``node_bound`` flow into the streaming trace
    ingest (traces/stream + resample): a trace-sourced spec that
    provably exceeds either bound raises ``TraceBoundExceeded``
    mid-read, before the rest of the trace is consumed."""
    from ksim_tpu.scenario.spec import (
        ScenarioSpecError,
        faults_spec_from_doc,
        operations_from_spec,
    )

    if not isinstance(doc, dict):
        raise ScenarioSpecError("job document must be a mapping")
    spec = doc.get("spec") or doc
    sim = spec.get("simulator") or {}
    for scope in (spec, sim):
        for banned in (
            "initialSnapshotPath",
            "scenarioTemplateFilePath",
            "scenarioResultFilePath",
        ):
            if banned in scope:
                raise ScenarioSpecError(
                    f"{banned} is not allowed in a tenant job spec — inline "
                    "the document (the job plane never reads server files)"
                )
    if sim.get("fleet"):
        # The fleet runner builds every lane's store/service itself —
        # a config/snapshot silently dropped here would run the wrong
        # simulation and still report Succeeded.  Refuse until fleet
        # lanes learn to carry them (ROADMAP "service round 2").
        for unsupported in ("schedulerConfig", "initialSnapshot"):
            if sim.get(unsupported):
                raise ScenarioSpecError(
                    f"simulator.{unsupported} is not supported together with "
                    "simulator.fleet (fleet lanes build default-config stores)"
                )
    scenario = spec.get("scenario")
    if scenario is None and "operations" in spec:
        scenario = {"operations": spec["operations"]}
    if scenario is None and "source" in spec:
        scenario = {"source": spec["source"]}
    if scenario is None:
        raise ScenarioSpecError(
            "job spec needs an inline scenario (spec.scenario.operations "
            "or spec.scenario.source.trace)"
        )
    ops = operations_from_spec(
        scenario,
        trace_resolver=_tenant_trace_resolver,
        event_bound=event_bound,
        node_bound=node_bound,
    )
    fault_spec = faults_spec_from_doc(doc)
    if fault_spec:
        for part in fault_spec.split(","):
            site = part.partition("=")[0]
            if site not in JOB_FAULT_SITES:
                raise ScenarioSpecError(
                    f"spec.faults site {site!r} is not a job-plane site "
                    f"(have {sorted(JOB_FAULT_SITES)})"
                )
    try:
        priority = int(spec.get("priority", 0))
    except (TypeError, ValueError):
        raise ScenarioSpecError("spec.priority must be an integer") from None
    return ops, dict(sim), priority, fault_spec


class Job:
    """One tenant job: spec + isolation planes + the event log the SSE
    stream replays.  Mutable state lives under ``_cond`` (the SSE
    readers wait on it); the trace/fault planes and the parsed ops are
    construction-time constants."""

    def __init__(
        self,
        job_id: str,
        ordinal: int,
        ops: list,
        sim: dict,
        priority: int,
        *,
        ring_cap: int,
        max_events: int,
        faults: "FaultPlane | None",
        tenant: str = "default",
    ) -> None:
        self.id = job_id
        self.ordinal = ordinal
        self.ops = ops
        self.sim = sim
        self.priority = priority
        self.faults = faults
        self.tenant = tenant
        self.cancel = threading.Event()
        self.created = time.time()
        self.steps_total = len({op.step for op in ops})
        # The job's PRIVATE trace plane: ring + histograms, every record
        # tagged with the job id; the sink feeds the SSE event log.
        self.trace = TracePlane(tags={"job": job_id})
        self.trace.configure_from_env(
            {"KSIM_TRACE_RING": str(ring_cap), "KSIM_TRACE": "1"}
        )
        self.trace.set_sink(self._on_record)
        self._max_events = max_events
        self._cond = threading.Condition()
        self.state = "queued"  # guarded-by: _cond
        self.error: "str | None" = None  # guarded-by: _cond
        self.result: "dict | None" = None  # guarded-by: _cond
        self.started: "float | None" = None  # guarded-by: _cond
        self.finished: "float | None" = None  # guarded-by: _cond
        self.steps_done = 0  # guarded-by: _cond
        self._events: list[dict] = []  # guarded-by: _cond
        self._dropped = 0  # guarded-by: _cond
        self.sse_listeners = 0  # guarded-by: _cond
        # The raw submitted document, kept ONLY once its submit record
        # is durably journaled (compaction re-serializes it; None in
        # the in-memory-only plane).
        self.doc: Any = None
        # Diagnostics handles, set by the worker (the job's own store/
        # runner — tests assert cancel-rollback consistency through
        # them; None for queued jobs).
        self.store = None
        self.runner = None
        # Incremental resume (docs/jobs.md): the journaled checkpoint
        # records recovery stashed for the worker's restore attempt
        # (single-threaded: written before the workers start, read only
        # by the one worker that claims the job), the NEWEST durable
        # checkpoint (re-emitted by compaction), and the status fields.
        self.checkpoints: list[dict] = []
        self._last_checkpoint: "dict | None" = None  # guarded-by: _cond
        self.checkpoint_segment: "int | None" = None  # guarded-by: _cond
        self.resumed_from: "int | None" = None  # guarded-by: _cond
        self._resume_info: "dict | None" = None  # worker-thread only
        # Fleet ownership (docs/jobs.md "Multi-worker fleet"): which
        # worker process holds the job's lease, folded from the lease
        # file by the front door's poller (or set locally on adoption).
        # None outside fleet mode — status() serves the keys either way.
        self.owner: "str | None" = None  # guarded-by: _cond
        self.lease_epoch: "int | None" = None  # guarded-by: _cond
        self.lease_ts: "float | None" = None  # guarded-by: _cond

    # -- event log (the SSE source) --------------------------------------

    def _emit_locked(self, ev: dict, vital: bool) -> None:  # ksimlint: lock-held(_cond)
        if not vital and len(self._events) >= self._max_events:
            self._dropped += 1
            return
        ev = dict(ev, seq=len(self._events), job=self.id)
        self._events.append(ev)
        self._cond.notify_all()

    def emit(self, ev: dict, *, vital: bool = False) -> None:
        with self._cond:
            self._emit_locked(ev, vital)

    def _on_record(self, rec: dict) -> None:
        """The job plane's record sink (called OUTSIDE the plane lock):
        reconcile/step spans become monotonically increasing progress
        events, instant trace events forward to the stream (droppable
        once the log caps out)."""
        name = rec.get("name")
        args = rec.get("args") or {}
        if rec.get("ph") == "X":
            if name == "runner.step":
                self._note_steps(1)
            elif name == "replay.reconcile" and "error" not in args:
                # Committed segments only: a rolled-back reconcile exits
                # its span with the error recorded, and its steps re-run
                # (head per-pass, rest on-device) — counting it would
                # double-book and break monotonic-progress semantics.
                self._note_steps(int(args.get("steps") or 0))
            return
        self.emit({"event": "trace", "name": name, "args": args})

    def _note_steps(self, n: int) -> None:
        if n <= 0:
            return
        with self._cond:
            self.steps_done += n
            self._emit_locked(
                {
                    "event": "progress",
                    "steps_done": self.steps_done,
                    "steps_total": self.steps_total,
                },
                True,
            )

    # -- state machine ---------------------------------------------------

    def claim(self) -> bool:
        """queued -> running (the worker's atomic take); False if the
        job was cancelled while queued."""
        with self._cond:
            if self.state != "queued" or self.cancel.is_set():
                return False
            self.state = "running"
            self.started = time.time()
            self._emit_locked({"event": "state", "state": "running"}, True)
            return True

    def finish(
        self,
        state: str,
        *,
        error: "str | None" = None,
        result: "dict | None" = None,
    ) -> None:
        with self._cond:
            if self.state in TERMINAL_STATES:
                return
            self.state = state
            self.error = error
            self.result = result
            self.finished = time.time()
            ev = {"event": "state", "state": state}
            if error:
                ev["error"] = error
            self._emit_locked(ev, True)

    def restore(
        self,
        state: str,
        *,
        error: "str | None" = None,
        result: "dict | None" = None,
        created: "float | None" = None,
        started: "float | None" = None,
        finished: "float | None" = None,
        cancelled: bool = False,
    ) -> None:
        """Journal-recovery only (JobManager._recover): install the
        reconstructed final state directly — the job never ran in THIS
        process, so the queued→running→terminal machinery must not
        fire (no worker owns it, no planes are scoped)."""
        if cancelled:
            self.cancel.set()
        with self._cond:
            self.state = state
            self.error = error
            self.result = result
            if created:
                self.created = float(created)
            self.started = float(started) if started else None
            self.finished = float(finished) if finished else time.time()
            ev = {"event": "state", "state": state, "recovered": True}
            if error:
                ev["error"] = error
            self._emit_locked(ev, True)

    def _set_lease(self, lease: dict) -> None:
        """Fleet: install the folded lease view (front door) or the
        just-claimed lease (worker adoption) for status()."""
        with self._cond:
            self.owner = lease.get("worker")
            self.lease_epoch = lease.get("epoch")
            ts = lease.get("ts")
            self.lease_ts = float(ts) if ts else None

    def _mirror_state(
        self,
        state: str,
        *,
        error: "str | None" = None,
        result: "dict | None" = None,
        started: "float | None" = None,
        finished: "float | None" = None,
        segment: "int | None" = None,
    ) -> None:
        """Fleet front door only: install a worker-journaled transition
        into this MIRROR job without emitting events — the per-job
        event file is the event authority (FleetMember forwards it into
        the ring), the shared journal the state authority.  A terminal
        mirror never regresses: a duplicate terminal record from the
        cancel race (front door finalized queued, worker journaled
        cancelled) folds to the same state."""
        with self._cond:
            if self.state in TERMINAL_STATES:
                return
            self.state = state
            if error is not None:
                self.error = error
            if result is not None:
                self.result = result
            if started:
                self.started = float(started)
            if state in TERMINAL_STATES:
                self.finished = float(finished) if finished else time.time()
                self.checkpoint_segment = None  # terminal: not carried
            else:
                self.checkpoint_segment = segment
            self._cond.notify_all()

    def request_cancel(self) -> str:
        """Set the cancel flag; a QUEUED job finalizes immediately, a
        RUNNING one stops at the runner's next checkpoint (rolling back
        any in-flight segment).  Returns the state after the request."""
        self.cancel.set()
        with self._cond:
            if self.state == "queued":
                self.state = "cancelled"
                self.finished = time.time()
                self._emit_locked({"event": "state", "state": "cancelled"}, True)
            return self.state

    def sse_attach(self) -> None:
        """One SSE reader subscribed (server/http.py pairs every attach
        with a detach in a finally — the leak regression test counts
        these through an aborted stream)."""
        with self._cond:
            self.sse_listeners += 1

    def sse_detach(self) -> None:
        with self._cond:
            self.sse_listeners = max(self.sse_listeners - 1, 0)

    # -- views -----------------------------------------------------------

    def status(self) -> dict:
        with self._cond:
            return {
                "id": self.id,
                "state": self.state,
                "priority": self.priority,
                "tenant": self.tenant,
                "created": round(self.created, 3),
                "started": round(self.started, 3) if self.started else None,
                "finished": round(self.finished, 3) if self.finished else None,
                "progress": {
                    "steps_done": self.steps_done,
                    "steps_total": self.steps_total,
                },
                "events": len(self._events),
                "events_dropped": self._dropped,
                "sse_listeners": self.sse_listeners,
                "cancel_requested": self.cancel.is_set(),
                "checkpoint_segment": self.checkpoint_segment,
                "resumed_from": self.resumed_from,
                "error": self.error,
                "owner": self.owner,
                "lease": (
                    {
                        "epoch": self.lease_epoch,
                        "age": round(time.time() - self.lease_ts, 3)
                        if self.lease_ts
                        else None,
                    }
                    if self.owner is not None
                    else None
                ),
            }

    def result_view(self) -> tuple[str, "dict | None", "str | None"]:
        with self._cond:
            return self.state, self.result, self.error

    def events_since(
        self, idx: int, timeout: "float | None" = None
    ) -> tuple[list[dict], int, bool]:
        """(new events from ``idx``, next index, end-of-stream).  Blocks
        up to ``timeout`` when nothing new exists and the job is still
        live — the SSE handler's poll step."""
        with self._cond:
            if idx >= len(self._events) and self.state not in TERMINAL_STATES:
                self._cond.wait(timeout)
            evs = list(self._events[idx:])
            nxt = idx + len(evs)
            done = self.state in TERMINAL_STATES and nxt >= len(self._events)
            return evs, nxt, done

    def wait_done(self, timeout: "float | None" = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self.state not in TERMINAL_STATES:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def trace_summary(self) -> dict:
        """The per-job plane snapshot trimmed for the merged metrics
        document: event counters, ring pressure, and per-span latency
        quantiles (the job's OWN p50/p99, not the process's)."""
        snap = self.trace.snapshot()
        return {
            "events": snap["events"],
            "ring": snap["ring"],
            "histograms": {
                name: {
                    k: h[k]
                    for k in ("count", "mean_seconds", "p50_seconds", "p99_seconds")
                    if k in h
                }
                for name, h in snap["histograms"].items()
            },
        }


class JobManager:
    """The worker pool + registry behind ``/api/v1/jobs``."""

    # Machine-checked acquisition order (tools/ksimlint lock-order —
    # docs/lint.md "Lock order").  Under the registry lock the submit
    # path notifies jobs/queue conditions and consults the planes;
    # the JOURNAL lock is never taken under the registry lock outside
    # construction-time recovery (waived inline in ``_recover``).
    # ksimlint: lock-order(JobManager._lock<Job._cond)
    # ksimlint: lock-order(JobManager._lock<JobQueue._cond)
    # ksimlint: lock-order(JobManager._lock<FaultPlane._lock)
    # ksimlint: lock-order(JobManager._lock<TracePlane._lock)

    def __init__(
        self,
        *,
        workers: "int | None" = None,
        queue_limit: "int | None" = None,
        ring_cap: "int | None" = None,
        keep: "int | None" = None,
        max_events: "int | None" = None,
        fault_spec: "str | None" = None,
        max_job_events: "int | None" = None,
        max_job_nodes: "int | None" = None,
        sjf_bypass: "int | None" = None,
        jobs_dir: "str | None" = None,
        resume: "bool | None" = None,
        journal_max_bytes: "int | None" = None,
        checkpoint_every: "int | None" = None,
        checkpoint_max_bytes: "int | None" = None,
        tenant_max_active: "int | None" = None,
        tenant_rate: "float | None" = None,
        role: "str | None" = None,
        worker_id: "str | None" = None,
        lease_s: "float | None" = None,
        heartbeat_s: "float | None" = None,
        poll_s: "float | None" = None,
    ) -> None:
        env = os.environ
        # Fleet role (docs/jobs.md "Multi-worker fleet"): None is the
        # solo manager, byte-identical to every pre-fleet round;
        # "frontdoor" serves HTTP over a mirror registry (zero local
        # workers); "worker" claims jobs by lease from the shared dir.
        if role is None:
            role = env.get("KSIM_WORKERS_ROLE", "") or None
        if role not in (None, "frontdoor", "worker"):
            raise ValueError(
                f"KSIM_WORKERS_ROLE must be 'frontdoor' or 'worker', "
                f"got {role!r}"
            )
        self.role = role
        if worker_id is None:
            worker_id = env.get("KSIM_WORKER_ID", "") or f"w{os.getpid()}"
        self.worker_id = str(worker_id)
        if lease_s is None:
            lease_s = float(env.get("KSIM_WORKERS_LEASE_S", "10"))
        if heartbeat_s is None:
            raw = env.get("KSIM_WORKERS_HEARTBEAT_S", "")
            heartbeat_s = float(raw) if raw else None
        if poll_s is None:
            poll_s = float(env.get("KSIM_WORKERS_POLL_S", "0.5"))
        if role == "frontdoor":
            workers = 0  # the front door never runs jobs locally
        if workers is None:
            workers = int(env.get("KSIM_JOBS_WORKERS", "2"))
        if queue_limit is None:
            queue_limit = int(env.get("KSIM_JOBS_QUEUE", "16"))
        if ring_cap is None:
            ring_cap = int(env.get("KSIM_JOBS_RING", "4096"))
        if keep is None:
            keep = int(env.get("KSIM_JOBS_KEEP", "64"))
        if max_events is None:
            max_events = int(env.get("KSIM_JOBS_EVENTS", "8192"))
        if fault_spec is None:
            fault_spec = env.get("KSIM_JOBS_FAULTS", "")
        if max_job_events is None:
            max_job_events = int(env.get("KSIM_JOBS_MAX_EVENTS", "0"))
        if max_job_nodes is None:
            max_job_nodes = int(env.get("KSIM_JOBS_MAX_NODES", "0"))
        if sjf_bypass is None:
            raw = env.get("KSIM_JOBS_SJF_BYPASS", "")
            sjf_bypass = int(raw) if raw else None
        if jobs_dir is None:
            jobs_dir = env.get("KSIM_JOBS_DIR", "")
        # Exposed for the fleet observability plane: the HTTP layer
        # resolves KSIM_JOBS_DIR/obs/ (published worker snapshots)
        # through the manager it already has.
        self.jobs_dir = jobs_dir or None
        if resume is None:
            resume = env.get("KSIM_JOBS_RESUME", "") == "1"
        if checkpoint_every is None:
            checkpoint_every = int(env.get("KSIM_JOBS_CHECKPOINT_EVERY", "8"))
        if checkpoint_max_bytes is None:
            checkpoint_max_bytes = int(
                env.get("KSIM_JOBS_CHECKPOINT_MAX_BYTES", str(64 * 1024 * 1024))
            )
        if tenant_max_active is None:
            tenant_max_active = int(env.get("KSIM_JOBS_TENANT_MAX_ACTIVE", "0"))
        if tenant_rate is None:
            tenant_rate = float(env.get("KSIM_JOBS_TENANT_RATE", "0"))
        # Checkpoint cadence/bounds (0 = off / unbounded) and tenant
        # admission bounds (0 = off) — docs/env.md "Job plane".
        self._checkpoint_every = max(int(checkpoint_every), 0)
        self._checkpoint_max_bytes = max(int(checkpoint_max_bytes), 0)
        self._tenant_max_active = max(int(tenant_max_active), 0)
        self._tenant_rate = max(float(tenant_rate), 0.0)
        # tenant -> token-bucket + counters (jobs section of the merged
        # metrics document).
        self._tenants: dict[str, dict] = {}  # guarded-by: _lock
        self._ring_cap = max(ring_cap, 16)
        self._keep = max(keep, 1)
        self._max_events = max(max_events, 64)
        # Per-submission resource bounds (0 = unbounded): HTTP 413.
        self._max_job_events = max(max_job_events, 0)
        self._max_job_nodes = max(max_job_nodes, 0)
        self._fault_specs = _job_fault_specs(fault_spec) if fault_spec else {}
        self.queue = JobQueue(queue_limit, max_bypass=sjf_bypass)
        self._lock = threading.Lock()
        self._jobs: "OrderedDict[str, Job]" = OrderedDict()  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._active = 0  # guarded-by: _lock
        # Durability: journal replay + registry reconstruction happen
        # BEFORE the workers start — recovery is single-threaded by
        # construction, so no claim can race the rebuild.
        self._journal: "JobJournal | None" = None
        if jobs_dir:
            self._journal = JobJournal(
                os.path.join(jobs_dir, JOURNAL_NAME),
                max_bytes=journal_max_bytes,
                # Fleet mode: other PROCESSES hold this journal open —
                # appends/compactions take the flock sidecar.
                shared=role is not None,
            )
            # Worker role NEVER replays at startup: the journal's
            # non-terminal jobs belong to whichever member holds their
            # lease (marking them interrupted here would sabotage a
            # live peer) — a worker's registry fills by adoption only.
            # The front door replays into MIRRORS: live states restore
            # verbatim, nothing is flagged interrupted, nothing is
            # re-enqueued locally.
            if role != "worker":
                self._recover(bool(resume) if role is None else False)
        self._threads: list[threading.Thread] = []
        for i in range(max(int(workers), 0)):
            t = threading.Thread(
                target=self._worker_loop, name=f"jobs-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        # The fleet poller starts LAST: adoption may enqueue onto the
        # local pool, so the workers must already be draining.
        self._fleet = None
        if role is not None and jobs_dir:
            from ksim_tpu.jobs.fleet import FleetMember

            self._fleet = FleetMember(
                self, jobs_dir, role=role, worker_id=self.worker_id,
                lease_s=lease_s, heartbeat_s=heartbeat_s, poll_s=poll_s,
            )
            self._fleet.start()

    # -- durability ------------------------------------------------------

    def _journal_append(self, rec: dict) -> bool:
        """One best-effort durable append.  False on failure (I/O error
        or an armed ``jobs.journal_append`` fault) — CALLERS decide the
        blast radius, which is always the ONE job the record belongs
        to, never the registry or the worker pool."""
        if self._journal is None:
            return True
        try:
            self._journal.append(rec)
            return True
        except Exception:
            logger.exception(
                "job journal append failed (type=%s job=%s)",
                rec.get("t"), rec.get("id"),
            )
            return False

    def _journal_state(
        self, job: Job, state: str, *, error: "str | None" = None
    ) -> bool:
        if self._journal is None:
            return True
        rec: dict = {
            "t": "state", "id": job.id, "state": state,
            "ts": round(time.time(), 3),
        }
        if error:
            rec["error"] = error
        return self._journal_append(rec)

    # Compaction's three-lock chain — the only path that ever holds
    # all three (journal first; the qualified lock-held below is what
    # lets the analyzer SEE the dynamic snapshot_fn callback):
    # ksimlint: lock-order(JobJournal._lock<JobManager._lock<Job._cond)
    def _journal_records(self) -> list[dict]:  # ksimlint: lock-held(JobJournal._lock)
        """The LIVE registry re-serialized as journal records — the
        compaction snapshot.  Called by ``JobJournal.maybe_compact``
        with the journal lock held; lock order journal ``_lock`` →
        manager ``_lock`` → job ``_cond`` (the only path that ever
        holds all three)."""
        recs: list[dict] = []
        for j in self.jobs():
            if j.doc is None:
                continue  # its submit record never became durable
            st = j.status()
            recs.append({
                "t": "submit", "id": j.id, "ordinal": j.ordinal,
                "priority": j.priority, "tenant": j.tenant, "doc": j.doc,
                "created": round(j.created, 3),
            })
            if st["started"]:
                recs.append({
                    "t": "state", "id": j.id, "state": "running",
                    "ts": st["started"],
                })
            if st["state"] not in TERMINAL_STATES:
                # A LIVE job keeps exactly its newest durable checkpoint
                # (older ones are dead weight once a newer one exists);
                # terminal jobs keep none — their result is the record.
                with j._cond:
                    ck = j._last_checkpoint
                if ck is not None:
                    recs.append(ck)
            if st["state"] in TERMINAL_STATES:
                _, result, _ = j.result_view()
                if result is not None:
                    recs.append({"t": "result", "id": j.id, "result": result})
                state_rec: dict = {
                    "t": "state", "id": j.id, "state": st["state"],
                    "ts": st["finished"],
                }
                if st["error"]:
                    state_rec["error"] = st["error"]
                recs.append(state_rec)
        return recs

    def _maybe_compact(self) -> None:
        """Bound the journal (called with NO locks held — submit's tail
        and the worker's run epilogue)."""
        if self._journal is not None:
            self._journal.maybe_compact(self._journal_records)

    def _recover(self, resume: bool) -> None:  # ksimlint: lock-held(_lock)
        """Rebuild the registry from the journal (startup, pre-workers).
        Runs in ``__init__`` BEFORE any worker thread exists, so the
        registry is single-threaded here by construction — the
        lock-held annotation records that exclusivity, not an actual
        acquisition.  Never raises: an unreadable journal (or an armed
        ``jobs.journal_replay`` fault) starts an empty registry; a
        per-job reconstruction failure loses that ONE job."""
        try:
            # Construction-time inversion of the compaction chain
            # (registry "lock" -> journal lock): waived, not blessed —
            # no worker thread exists yet, so no second thread can hold
            # the journal lock against us.
            recs = self._journal.replay()  # ksimlint: disable=lock-order
        except Exception:
            logger.exception(
                "job journal replay failed; starting with an empty registry"
            )
            return
        folded: "OrderedDict[str, dict]" = OrderedDict()
        for rec in recs:
            jid, t = rec.get("id"), rec.get("t")
            if not isinstance(jid, str):
                continue
            ent = folded.setdefault(jid, {
                "submit": None, "state": None, "error": None,
                "result": None, "cancel": False,
                "started": None, "finished": None,
                "checkpoints": [], "history": [],
            })
            if t == "submit":
                ent["submit"] = rec
            elif t == "state":
                state = rec.get("state")
                ent["state"], ent["error"] = state, rec.get("error")
                if state == "running":
                    ent["started"] = rec.get("ts")
                elif state in TERMINAL_STATES:
                    ent["finished"] = rec.get("ts")
                # The full transition history, in journal order — the
                # resumed job's SSE backlog replays it so a reconnecting
                # tenant's stream is gap-free across the restart.
                ent["history"].append({
                    "state": state, "ts": rec.get("ts"),
                    "error": rec.get("error"),
                })
            elif t == "result":
                ent["result"] = rec.get("result")
            elif t == "cancel":
                ent["cancel"] = True
            elif t == "checkpoint":
                ent["checkpoints"].append(rec)
        interrupted = resumed = 0
        max_ordinal = -1
        for jid, ent in folded.items():
            sub = ent["submit"]
            if sub is None:
                continue  # debris past compaction: states without a spec
            try:
                ordinal = int(sub.get("ordinal", 0))
                priority = int(sub.get("priority", 0))
                max_ordinal = max(max_ordinal, ordinal)
                job: "Job | None" = None
                # Resumable: died mid-flight (no terminal record) OR
                # already flagged interrupted by an earlier restart —
                # KSIM_JOBS_RESUME=1 is exactly the "re-run those"
                # switch, so it must reach jobs a resume-less restart
                # already journaled as interrupted.
                resumable = (
                    ent["state"] not in TERMINAL_STATES
                    or ent["state"] == "interrupted"
                )
                if resumable and resume:
                    job = self._resume_job(jid, ordinal, priority, sub, ent)
                    if job is not None:
                        resumed += 1
                if job is None:
                    # Same construction-time waiver as replay() above.
                    job = self._restore_job(jid, ordinal, priority, sub, ent)  # ksimlint: disable=lock-order
                    if job.status()["state"] == "interrupted":
                        interrupted += 1
                self._jobs[jid] = job
            except Exception:
                logger.exception("job journal recovery lost job %s", jid)
        self._seq = max_ordinal + 1
        TRACE.event(
            "jobs.journal_recover",
            jobs=len(self._jobs), interrupted=interrupted, resumed=resumed,
            truncated_bytes=self._journal.truncated_bytes,
        )

    def _restore_job(
        self, jid: str, ordinal: int, priority: int, sub: dict, ent: dict
    ) -> Job:
        """One journal-reconstructed job: terminal states restore
        verbatim (the result document serves byte-identically); a job
        last seen queued/running died with the old process and is
        flagged ``interrupted``.

        Fleet front door EXCEPTION: a restarting front door's
        non-terminal jobs are (probably) still running on a live worker
        — they restore as LIVE mirrors with the journaled state
        verbatim, no interrupted flag, no interrupted record (which a
        worker would read as terminal and skip the job forever).  If
        the owner really is dead, lease expiry hands the job to a
        survivor and the mirror catches up."""
        job = Job(
            jid, ordinal, [], {}, priority,
            ring_cap=self._ring_cap, max_events=self._max_events, faults=None,
            tenant=str(sub.get("tenant") or "default"),
        )
        job.doc = sub.get("doc")
        state = ent["state"]
        if state in TERMINAL_STATES:
            job.restore(
                state,
                error=ent["error"],
                result=ent["result"] if state == "succeeded" else None,
                created=sub.get("created"), started=ent["started"],
                finished=ent["finished"], cancelled=ent["cancel"],
            )
        elif self.role == "frontdoor":
            if ent["cancel"]:
                job.cancel.set()
            with job._cond:
                job.state = state or "queued"
                if sub.get("created"):
                    job.created = float(sub["created"])
                job.started = (
                    float(ent["started"]) if ent["started"] else None
                )
            # Gap-free SSE across the front-door restart: replay the
            # journaled lifecycle into the fresh mirror ring first; the
            # event-file tailer appends the live tail on top.
            for h in ent.get("history", ()):
                ev = {"event": "state", "state": h["state"],
                      "recovered": True}
                if h.get("error"):
                    ev["error"] = h["error"]
                job.emit(ev, vital=True)
            if ent["checkpoints"]:
                with job._cond:
                    job.checkpoint_segment = (
                        ent["checkpoints"][-1].get("segment")
                    )
        else:
            job.restore(
                "interrupted",
                error="interrupted by server restart",
                created=sub.get("created"), started=ent["started"],
                cancelled=ent["cancel"],
            )
            self._journal_state(job, "interrupted",
                                error="interrupted by server restart")
        return job

    def _resume_job(
        self, jid: str, ordinal: int, priority: int, sub: dict, ent: dict
    ) -> "Job | None":
        """KSIM_JOBS_RESUME=1: re-parse the journaled spec and re-enqueue
        the died-mid-run job under its original id/ordinal, carrying its
        journaled checkpoints for the worker's incremental restore.
        None when the spec no longer parses or the queue is full — the
        caller falls back to ``interrupted`` (recovery never crashes
        startup)."""
        try:
            ops, sim, _, fault_spec = _parse_job_spec(sub.get("doc"))
            entries = list(self._fault_specs.get(ordinal, ()))
            if fault_spec:
                entries.append(fault_spec)
            faults: "FaultPlane | None" = None
            if entries and not sim.get("fleet"):
                faults = FaultPlane()
                for entry in entries:
                    faults.configure(entry)
            job = Job(
                jid, ordinal, ops, sim, priority,
                ring_cap=self._ring_cap, max_events=self._max_events,
                faults=faults, tenant=str(sub.get("tenant") or "default"),
            )
            job.doc = sub.get("doc")
            # Gap-free SSE across the restart: replay the journaled
            # lifecycle transitions into the fresh event log FIRST, so a
            # reconnecting tenant streaming from index 0 sees the
            # pre-restart history (queued→running→...) ahead of the
            # re-enqueue — not a log that starts mid-life.
            for h in ent.get("history", ()):
                ev = {"event": "state", "state": h["state"], "recovered": True}
                if h.get("error"):
                    ev["error"] = h["error"]
                job.emit(ev, vital=True)
            job.checkpoints = list(ent.get("checkpoints", ()))
            if job.checkpoints:
                last = job.checkpoints[-1]
                with job._cond:
                    job._last_checkpoint = last
                    job.checkpoint_segment = last.get("segment")
            job.emit({"event": "state", "state": "queued", "resumed": True},
                     vital=True)
            self.queue.put(job, priority=priority, cost=len(ops))
            return job
        except Exception:
            logger.exception("job %s could not be resumed", jid)
            return None

    # -- fleet adoption --------------------------------------------------

    def adopt(self, jid: str, ent: dict,
              lease: "dict | None" = None) -> "Job | None":
        """Fleet worker: take ownership of a journal-folded job this
        process just LEASED (FleetMember's poller, after a winning
        ``LeasePlane.claim``) — the cross-process twin of
        ``_resume_job``.  Re-parses the journaled spec, replays the
        journaled lifecycle into the event log (tagged ``recovered``),
        carries the folded checkpoints for the round-16 incremental
        restore, and enqueues onto the LOCAL pool under the original
        id/ordinal.  ``JobQueueFull`` propagates — local backpressure
        is retryable, the caller keeps the lease and tries again.  A
        spec that no longer parses journals a terminal ``failed``
        record (so the front door mirrors the refusal) and returns
        None."""
        sub = ent.get("submit") or {}
        ordinal = int(sub.get("ordinal", 0))
        priority = int(sub.get("priority", 0))
        existing = self.get(jid)
        if existing is not None:
            return existing
        try:
            ops, sim, _, fault_spec = _parse_job_spec(sub.get("doc"))
            entries = list(self._fault_specs.get(ordinal, ()))
            if fault_spec:
                entries.append(fault_spec)
            faults: "FaultPlane | None" = None
            if entries and not sim.get("fleet"):
                faults = FaultPlane()
                for entry in entries:
                    faults.configure(entry)
        except Exception as e:
            error = f"adopted spec no longer parses: {type(e).__name__}: {e}"
            logger.exception("job %s could not be adopted", jid)
            self._journal_append({
                "t": "state", "id": jid, "state": "failed", "error": error,
                "ts": round(time.time(), 3),
            })
            return None
        job = Job(
            jid, ordinal, ops, sim, priority,
            ring_cap=self._ring_cap, max_events=self._max_events,
            faults=faults, tenant=str(sub.get("tenant") or "default"),
        )
        job.doc = sub.get("doc")
        if sub.get("created"):
            job.created = float(sub["created"])
        for h in ent.get("history", ()):
            ev = {"event": "state", "state": h["state"], "recovered": True}
            if h.get("error"):
                ev["error"] = h["error"]
            job.emit(ev, vital=True)
        job.checkpoints = list(ent.get("checkpoints", ()))
        if job.checkpoints:
            last = job.checkpoints[-1]
            with job._cond:
                job._last_checkpoint = last
                job.checkpoint_segment = last.get("segment")
        if ent.get("cancel"):
            job.cancel.set()
        job._set_lease(lease or {"worker": self.worker_id,
                                 "ts": time.time()})
        job.emit({"event": "state", "state": "queued", "resumed": True},
                 vital=True)
        # JobQueueFull propagates with no registry residue.
        self.queue.put(job, priority=priority, cost=len(ops))
        with self._lock:
            self._seq = max(self._seq, ordinal + 1)
            self._jobs[jid] = job
            self._prune_locked()
        TRACE.event("jobs.enqueue", job=jid, priority=priority,
                    depth=self.queue.depth())
        return job

    # -- submission ------------------------------------------------------

    def submit(
        self,
        doc: Any,
        *,
        priority: "int | None" = None,
        tenant: "str | None" = None,
    ) -> Job:
        """Validate + enqueue one tenant job document.  Raises
        ``ScenarioSpecError`` on a bad spec (HTTP 400),
        ``JobLimitExceeded`` when the spec exceeds the operator's
        per-job bounds (HTTP 413), ``JobThrottled`` when the tenant is
        over its quota/rate (HTTP 429 + Retry-After), and
        ``JobQueueFull`` on a saturated queue (HTTP 429).

        ``tenant`` (the HTTP layer's ``X-Ksim-Tenant`` header) wins
        over ``spec.tenant``; absent both, jobs pool under ``default``.

        The submission ordinal (the ``KSIM_JOBS_FAULTS`` key) commits
        only on a SUCCESSFUL enqueue: a refused submission must not
        shift which job an armed chaos schedule lands on (that would be
        the vacuously-green sweep the fault parsers exist to refuse).
        The whole reserve-build-enqueue sequence runs under the manager
        lock, so concurrent submits cannot interleave ordinals with
        rejections; lock order is ``_lock`` → ``queue._cond`` →
        ``job._cond``, matching every other path."""
        from ksim_tpu.traces.schema import TraceBoundExceeded

        try:
            ops, sim, spec_priority, fault_spec = _parse_job_spec(
                doc,
                event_bound=self._max_job_events,
                node_bound=self._max_job_nodes,
            )
        except TraceBoundExceeded as e:
            # Streaming ingest proved the bound exceeded MID-READ and
            # stopped consuming trace bytes; translate to the job
            # plane's vocabulary (HTTP 413, same as the post-parse
            # checks below).
            env = (
                "KSIM_JOBS_MAX_EVENTS"
                if e.kind == "events"
                else "KSIM_JOBS_MAX_NODES"
            )
            raise JobLimitExceeded(
                f"job trace compiles to at least {e.observed} {e.kind}, "
                f"over the per-job bound of {e.limit} ({env}); ingest "
                "stopped early"
            ) from None
        if priority is None:
            priority = spec_priority
        if tenant is None:
            scope = (doc.get("spec") or doc) if isinstance(doc, dict) else {}
            tenant = str(scope.get("tenant") or "") or "default"
        # Resource bounds for inline specs (trace-sourced specs are
        # bounded during streaming ingest above): what is measured is
        # the stream the job would actually replay.
        if self._max_job_events and len(ops) > self._max_job_events:
            raise JobLimitExceeded(
                f"job spec compiles to {len(ops)} events, over the "
                f"per-job bound of {self._max_job_events} "
                "(KSIM_JOBS_MAX_EVENTS)"
            )
        if self._max_job_nodes:
            n_nodes = sum(
                1 for op in ops if op.kind == "nodes" and op.op == "create"
            )
            if n_nodes > self._max_job_nodes:
                raise JobLimitExceeded(
                    f"job spec creates {n_nodes} nodes, over the per-job "
                    f"bound of {self._max_job_nodes} (KSIM_JOBS_MAX_NODES)"
                )
        with self._lock:
            # Tenant admission BEFORE the ordinal reservation: a
            # throttled submission must not shift which job an armed
            # KSIM_JOBS_FAULTS ordinal lands on, same as every other
            # refusal in this block.
            self._admit_tenant_locked(tenant)
            ordinal = self._seq
            # The job's private plane is built FRESH per submission from
            # the operator's per-ordinal schedules plus the spec's own
            # faults section (a refused submission leaves nothing armed;
            # FaultPlane.configure rejects malformed schedules loudly
            # -> HTTP 400).
            entries = list(self._fault_specs.get(ordinal, ()))
            if fault_spec:
                entries.append(fault_spec)
            faults: "FaultPlane | None" = None
            if entries:
                from ksim_tpu.scenario.spec import ScenarioSpecError

                faults = FaultPlane()
                try:
                    for entry in entries:
                        faults.configure(entry)
                except ValueError as e:
                    raise ScenarioSpecError(f"spec.faults: {e}") from None
            if faults is not None and sim.get("fleet"):
                from ksim_tpu.scenario.spec import ScenarioSpecError

                # The private plane is checked on the SOLO replay path
                # only; silently dropping it for a fleet job would run
                # the chaos schedule against nothing.
                raise ScenarioSpecError(
                    f"chaos is armed for job ordinal {ordinal} "
                    "(KSIM_JOBS_FAULTS or spec.faults), but the submitted "
                    "job is a fleet job — per-lane chaos uses "
                    "KSIM_FLEET_FAULTS (docs/faults.md)"
                )
            job = Job(
                f"job-{ordinal:06d}",
                ordinal,
                ops,
                sim,
                priority,
                ring_cap=self._ring_cap,
                max_events=self._max_events,
                faults=faults,
                tenant=tenant,
            )
            # The queued event lands BEFORE the queue hand-off: once
            # put() returns, a worker may claim (and emit "running")
            # immediately, and the SSE log's state order must match
            # reality.
            job.emit({"event": "state", "state": "queued"}, vital=True)
            # Cost-aware admission: the spec's event count is the cost
            # estimate (shortest-job-first within the priority band).
            # The fleet FRONT DOOR never enqueues locally — its journal
            # submit record IS the hand-off, and a worker process
            # claims it by lease; backpressure there is per-tenant
            # admission plus the workers' own queue capacity.
            if self.role != "frontdoor":
                self.queue.put(
                    job, priority=priority, cost=len(ops)
                )  # JobQueueFull -> no ordinal
            self._seq += 1
            self._jobs[job.id] = job
            self._prune_locked()
        # WAL: the submit record lands OUTSIDE the manager lock (lock
        # order — the journal lock is taken first on the compaction
        # path, so it must never nest inside ``_lock``).  A failed
        # append fails the ONE job: the worker's ``claim()`` then sees
        # a terminal state and skips it; the registry stays clean.
        if self._journal is not None:
            ok = self._journal_append({
                "t": "submit", "id": job.id, "ordinal": job.ordinal,
                "priority": priority, "tenant": job.tenant, "doc": doc,
                "created": round(job.created, 3),
            })
            if ok:
                job.doc = doc
            else:
                job.finish("failed", error="journal append failed (submit)")
        TRACE.event(
            "jobs.enqueue", job=job.id, priority=priority, depth=self.queue.depth()
        )
        self._maybe_compact()
        return job

    def _admit_tenant_locked(self, tenant: str) -> None:  # ksimlint: lock-held(_lock)
        """Per-tenant admission (ROADMAP service round 4 (c)): the
        concurrency quota counts the tenant's non-terminal jobs in the
        registry; the rate limit is a token bucket refilled at
        ``KSIM_JOBS_TENANT_RATE`` tokens/s with burst
        ``max(rate, 1)``.  Raises ``JobThrottled`` with a computed
        ``retry_after`` — for the bucket it is exactly the time until
        the next token, for the quota a fixed re-poll hint (job
        durations are unknowable at admission)."""
        ent = self._tenants.get(tenant)
        if ent is None:
            ent = self._tenants[tenant] = {
                "tokens": max(self._tenant_rate, 1.0),
                "last": time.monotonic(),
                "admitted": 0,
                "throttled": 0,
            }
        if self._tenant_max_active:
            active = sum(
                1
                for j in self._jobs.values()
                if j.tenant == tenant
                and j.status()["state"] not in TERMINAL_STATES
            )
            if active >= self._tenant_max_active:
                ent["throttled"] += 1
                raise JobThrottled(
                    f"tenant {tenant!r} has {active} active jobs, at the "
                    f"per-tenant bound of {self._tenant_max_active} "
                    "(KSIM_JOBS_TENANT_MAX_ACTIVE)",
                    retry_after=5.0,
                )
        if self._tenant_rate:
            now = time.monotonic()
            burst = max(self._tenant_rate, 1.0)
            ent["tokens"] = min(
                burst, ent["tokens"] + (now - ent["last"]) * self._tenant_rate
            )
            ent["last"] = now
            if ent["tokens"] < 1.0:
                ent["throttled"] += 1
                raise JobThrottled(
                    f"tenant {tenant!r} is over the sustained submission "
                    f"rate of {self._tenant_rate:g}/s "
                    "(KSIM_JOBS_TENANT_RATE)",
                    retry_after=(1.0 - ent["tokens"]) / self._tenant_rate,
                )
            ent["tokens"] -= 1.0
        ent["admitted"] += 1

    def _prune_locked(self) -> None:  # ksimlint: lock-held(_lock)
        """Bound the registry: drop the oldest TERMINAL jobs beyond the
        retention limit (live jobs are never dropped — the bounded
        queue is what limits those)."""
        if len(self._jobs) <= self._keep:
            return
        for jid in list(self._jobs):
            if len(self._jobs) <= self._keep:
                break
            j = self._jobs[jid]
            if j.status()["state"] in TERMINAL_STATES:
                del self._jobs[jid]

    # -- the workers -----------------------------------------------------

    def _worker_loop(self) -> None:  # ksimlint: thread-role(job-worker)
        while True:
            job = self.queue.get()
            if job is None:
                return
            if not job.claim():
                continue  # cancelled while queued
            with self._lock:
                self._active += 1
            try:
                self._run_job(job)
            finally:
                with self._lock:
                    self._active -= 1

    def _run_job(self, job: Job) -> None:
        """Run one job inside its scoped planes.  The global TRACE's
        scoped override routes every span/event of the whole pipeline —
        runner, service, replay driver, even the dispatch worker thread
        (the executor re-installs the scope there) — onto the job's
        private plane, tagged ``job=<id>``."""
        # WAL: the running record lands BEFORE any work — a restart
        # that finds it (and no terminal record) knows the job died
        # mid-run and flags it ``interrupted``.  An unappendable
        # journal fails the job without running it.
        if not self._journal_state(job, "running"):
            job.finish("failed", error="journal append failed (running)")
            return
        try:
            with TRACE.scoped(job.trace):
                with TRACE.span("jobs.run", steps=job.steps_total):
                    FAULTS.check("jobs.run")
                    if job.faults is not None:
                        job.faults.check("jobs.run")
                    res, runner = self._execute(job)
            result = self._result_doc(job, res, runner)
            # WAL: result + terminal record become durable BEFORE the
            # in-memory success — a success the journal cannot vouch
            # for must not be reported (it would vanish on restart).
            if self._journal is not None:
                ok = self._journal_append(
                    {"t": "result", "id": job.id, "result": result}
                ) and self._journal_state(job, "succeeded")
                if not ok:
                    job.finish("failed", error="journal append failed (result)")
                    return
            job.finish("succeeded", result=result)
        except RunCancelled:
            job.finish("cancelled")
            self._journal_state(job, "cancelled")  # best-effort: terminal
            logger.info("job %s cancelled", job.id)
        except Exception as e:
            logger.exception("job %s failed", job.id)
            error = f"{type(e).__name__}: {e}"
            job.finish("failed", error=error)
            self._journal_state(job, "failed", error=error)  # best-effort
        finally:
            self._maybe_compact()

    def _execute(self, job: Job):
        """Build the job's isolated simulator stack from its spec and
        replay the scenario.  Imported lazily: the manager is
        constructible (and the queue/metrics surface usable) without
        pulling the scheduler/jax stack into a process that never runs
        a job."""
        from ksim_tpu.scenario.runner import ScenarioRunner
        from ksim_tpu.scheduler.service import SchedulerService
        from ksim_tpu.state.cluster import ClusterStore

        sim = job.sim
        fleet = sim.get("fleet")
        if fleet:
            runner = ScenarioRunner(
                record=sim.get("recordMode", "selection"),
                preemption=bool(sim.get("preemption", False)),
                max_pods_per_pass=sim.get("maxPodsPerPass"),
                pod_bucket_min=sim.get("podBucketMin"),
                device_replay=True,
                fleet=int(fleet),
                cancel=job.cancel,
            )
            job.runner = runner
            res = runner.run(job.ops)
            return res, runner
        # Solo path: restore from the newest valid journaled checkpoint
        # when recovery carried any (KSIM_JOBS_RESUME=1), else build
        # fresh; either way the runner gets the checkpoint-cadence hook.
        resume_cursor = 0
        resume_result = None
        store = service = None
        if job.checkpoints:
            restored = self._restore_checkpoint(job, sim)
            if restored is not None:
                store, service, resume_cursor, resume_result = restored
        if store is None:
            store = ClusterStore()
            if sim.get("initialSnapshot"):
                from ksim_tpu.state.snapshot import SnapshotService

                SnapshotService(store).load(sim["initialSnapshot"])
            service = SchedulerService(
                store,
                config=sim.get("schedulerConfig"),
                record=sim.get("recordMode", "selection"),
                preemption=bool(sim.get("preemption", False)),
                max_pods_per_pass=sim.get("maxPodsPerPass"),
                pod_bucket_min=sim.get("podBucketMin"),
            )
        hook = None
        if self._journal is not None and self._checkpoint_every > 0:
            hook = self._checkpoint_hook_for(job, store, service)
        runner = ScenarioRunner(
            store=store,
            service=service,
            device_replay=bool(sim.get("deviceReplay", False)),
            cancel=job.cancel,
            private_faults=job.faults,
            checkpoint_hook=hook,
        )
        job.store = store
        job.runner = runner
        res = runner.run(
            job.ops, resume_cursor=resume_cursor, resume_result=resume_result
        )
        return res, runner

    def _checkpoint_hook_for(self, job: Job, store, service):
        """The runner's post-commit segment callback: every
        ``KSIM_JOBS_CHECKPOINT_EVERY``-th COMMITTED segment appends one
        checkpoint record.  Committed segments are counted here (not
        ``segment_seq``, which also counts segments that later rolled
        back) so the cadence is exactly "every N durable advances"."""
        state = {"committed": 0, "seq": 0}

        def hook(cursor: int, driver, result) -> None:
            state["committed"] += 1
            if state["committed"] % self._checkpoint_every:
                return
            state["seq"] += 1
            self._append_checkpoint(
                job, store, service, cursor, driver, result, state["seq"]
            )

        return hook

    def _append_checkpoint(
        self, job: Job, store, service, cursor: int, driver, result, seq: int
    ) -> None:
        """Build + durably append one segment checkpoint.  Best-effort
        by contract: a non-restorable moment (Permit-waiting pods), an
        oversized snapshot, or any append/snapshot failure SKIPS the
        checkpoint with a counted ``jobs.checkpoint`` event — the run
        itself must never degrade because its insurance did."""
        try:
            with TRACE.span(
                "jobs.checkpoint_append", job=job.id, cursor=cursor
            ):
                FAULTS.check("jobs.checkpoint_append")
                carries = service.checkpoint_carries()
                if carries.pop("waiting"):
                    # Pods parked in a Permit plugin's waiting map are
                    # scheduling state with no restore story — resuming
                    # without them would double-admit or drop them.
                    TRACE.event(
                        "jobs.checkpoint", job=job.id,
                        skipped=True, reason="waiting_pods",
                    )
                    return
                rec = {
                    "t": "checkpoint",
                    "id": job.id,
                    "seq": seq,
                    "cursor": int(cursor),
                    "segment": int(driver.segment_seq),
                    # Restore-time identity check (round 19): a resume
                    # whose simulator spec changed must NOT consume
                    # this record (see _spec_hash / _restore_checkpoint).
                    "spec": _spec_hash(job.sim),
                    "store": store.checkpoint(),
                    "service": carries,
                    "result": {
                        "events_applied": result.events_applied,
                        "pods_scheduled": result.pods_scheduled,
                        "unschedulable_attempts": result.unschedulable_attempts,
                        "steps": [
                            [
                                s.step, s.ops_applied, s.scheduled,
                                s.unschedulable, s.pending_after,
                            ]
                            for s in result.steps
                        ],
                    },
                    "ts": round(time.time(), 3),
                }
                size = len(json.dumps(rec, separators=(",", ":")))
                if self._checkpoint_max_bytes and size > self._checkpoint_max_bytes:
                    TRACE.event(
                        "jobs.checkpoint", job=job.id, skipped=True,
                        reason="max_bytes", bytes=size,
                    )
                    return
                if not self._journal_append(rec):
                    TRACE.event(
                        "jobs.checkpoint", job=job.id,
                        skipped=True, reason="append_failed",
                    )
                    return
                with job._cond:
                    job._last_checkpoint = rec
                    job.checkpoint_segment = rec["segment"]
                TRACE.event(
                    "jobs.checkpoint", job=job.id, cursor=cursor,
                    segment=rec["segment"], bytes=size,
                )
        except Exception:
            # Injected jobs.checkpoint_append faults and unexpected
            # snapshot failures land here: counted, contained, the run
            # continues (and retries at the next cadence point).
            logger.exception("job %s checkpoint append failed", job.id)
            TRACE.event(
                "jobs.checkpoint", job=job.id,
                skipped=True, reason="append_failed",
            )

    def _restore_checkpoint(self, job: Job, sim: dict):
        """Newest-first restore attempts over the job's journaled
        checkpoints (worker thread — the only place the jax/scheduler
        stack may load).  Returns (store, service, cursor, partial
        result) or None (every checkpoint unusable → replay from
        scratch).  A failed attempt falls back to the PREVIOUS
        checkpoint: the mid-file analogue of the journal's torn-tail
        rule, which already drops a checkpoint torn mid-append before
        recovery ever sees it."""
        from ksim_tpu.scenario.runner import ScenarioResult, StepResult
        from ksim_tpu.scheduler.service import SchedulerService
        from ksim_tpu.state.cluster import ClusterStore

        want = _spec_hash(sim)
        for rec in reversed(job.checkpoints):
            seg = rec.get("segment")
            got = rec.get("spec")
            if got is not None and got != want:
                # Round 19 (the code half of "Resume across a config
                # change", docs/jobs.md): the checkpoint was cut under a
                # DIFFERENT simulator spec — restoring its carries into
                # a service built from the new config would silently
                # diverge, so the record is refused (counted, loud) and
                # the scan falls through to older records; when every
                # checkpoint predates the change the job replays from
                # scratch — the correct-but-slow outcome the doc
                # promises.  Records without a "spec" field (pre-round-
                # 19 journals) restore as before.
                TRACE.event(
                    "jobs.checkpoint_restore", job=job.id, restored=False,
                    segment=seg, reason="spec_hash",
                )
                continue
            try:
                with TRACE.span(
                    "jobs.checkpoint_restore", job=job.id, segment=seg
                ):
                    FAULTS.check("jobs.checkpoint_restore")
                    store = ClusterStore.from_checkpoint(rec["store"])
                    # The service rebuilds from the SPEC (its config is
                    # deterministic given the document); the
                    # initialSnapshot is deliberately NOT re-loaded —
                    # its objects are already inside the restored store.
                    service = SchedulerService(
                        store,
                        config=sim.get("schedulerConfig"),
                        record=sim.get("recordMode", "selection"),
                        preemption=bool(sim.get("preemption", False)),
                        max_pods_per_pass=sim.get("maxPodsPerPass"),
                        pod_bucket_min=sim.get("podBucketMin"),
                    )
                    service.restore_carries(rec.get("service") or {})
                    acc = rec.get("result") or {}
                    result = ScenarioResult(
                        events_applied=int(acc.get("events_applied", 0)),
                        pods_scheduled=int(acc.get("pods_scheduled", 0)),
                        unschedulable_attempts=int(
                            acc.get("unschedulable_attempts", 0)
                        ),
                    )
                    for row in acc.get("steps") or ():
                        result.steps.append(
                            StepResult(*[int(v) for v in row])
                        )
                    cursor = int(rec["cursor"])
            except Exception as e:
                logger.exception(
                    "job %s checkpoint (segment %s) unusable; falling "
                    "back to the previous one", job.id, seg,
                )
                TRACE.event(
                    "jobs.checkpoint_restore", job=job.id, restored=False,
                    segment=seg, error=type(e).__name__,
                )
                continue
            TRACE.event(
                "jobs.checkpoint_restore", job=job.id, restored=True,
                segment=seg, cursor=cursor,
            )
            with job._cond:
                job.resumed_from = seg
                job.checkpoint_segment = seg
                # The progress baseline: the restored steps are done,
                # only suffix segments/passes add to it from here.
                job.steps_done = len(result.steps)
            job._resume_info = {
                "fromSegment": seg,
                "cursor": cursor,
                "carried_events": result.events_applied,
            }
            return store, service, cursor, result
        return None

    def _result_doc(self, job: Job, res, runner) -> dict:
        doc: dict = {
            "phase": "Succeeded",
            "done": res.succeeded,
            "result": {
                "eventsApplied": res.events_applied,
                "podsScheduled": res.pods_scheduled,
                "unschedulableAttempts": res.unschedulable_attempts,
                "wallSeconds": round(res.wall_seconds, 3),
                "steps": len(res.steps),
            },
            "phases": dict(res.phase_seconds),
            # The job's OWN latency quantiles (its private histograms).
            "latency": job.trace_summary()["histograms"],
        }
        if res.lanes is not None:
            doc["lanes"] = [
                [r.pods_scheduled, r.unschedulable_attempts] for r in res.lanes
            ]
        info = job._resume_info
        if info is not None:
            # eventsReplayed counts only THIS process's suffix — the
            # restart-check/bench evidence that an incremental resume
            # did strictly less work than a from-scratch replay.
            doc["resume"] = {
                "fromSegment": info["fromSegment"],
                "cursor": info["cursor"],
                "eventsReplayed": res.events_applied - info["carried_events"],
            }
        drv = getattr(runner, "replay_driver", None)
        if drv is not None:
            doc["replay"] = drv.stats()  # includes the shared compile_cache
        return doc

    # -- lookups & lifecycle --------------------------------------------

    def get(self, job_id: str) -> "Job | None":
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())

    def cancel(self, job_id: str) -> "str | None":
        """Request cancellation; returns the post-request state, or
        None for an unknown job."""
        job = self.get(job_id)
        if job is None:
            return None
        already_done = job.status()["state"] in TERMINAL_STATES
        state = job.request_cancel()
        if not already_done:
            TRACE.event("job.cancelled", job=job.id, state=state)
            # Best-effort WAL: the cancel REQUEST, plus the terminal
            # record when the queued job finalized right here (a
            # running job's terminal record comes from its worker).
            self._journal_append(
                {"t": "cancel", "id": job.id, "ts": round(time.time(), 3)}
            )
            if state == "cancelled":
                self._journal_state(job, "cancelled")
        return state

    def join(self, timeout: "float | None" = None) -> bool:
        """Wait for every registered job to reach a terminal state
        (tests / bench).  True when all finished inside the timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for job in self.jobs():
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return False
            if not job.wait_done(remaining):
                return False
        return True

    def snapshot(self) -> dict:
        """The ``jobs`` section of /api/v1/metrics: queue depth, worker
        occupancy, and per-job status + private-plane summaries."""
        with self._lock:
            jobs = list(self._jobs.values())
            active = self._active
            tenants = {
                t: {
                    "admitted": e["admitted"],
                    "throttled": e["throttled"],
                    "tokens": round(e["tokens"], 3),
                }
                for t, e in self._tenants.items()
            }
        doc = {
            "queue": self.queue.stats(),
            "workers": {"pool": len(self._threads), "active": active},
            "tenants": tenants,
            "jobs": {
                j.id: dict(j.status(), trace=j.trace_summary()) for j in jobs
            },
        }
        if self._journal is not None:
            doc["journal"] = self._journal.snapshot()
        if self._fleet is not None:
            doc["fleet"] = self._fleet.snapshot()
        return doc

    def shutdown(self, timeout: "float | None" = 5.0) -> None:
        """Stop accepting work, cancel everything live, and join the
        workers (daemon threads — a stuck dispatch cannot block process
        exit, it is simply abandoned like the replay watchdog's).  The
        fleet poller stops LAST so its final drain forwards the jobs'
        terminal events and releases the now-terminal leases."""
        self.queue.close()
        for job in self.jobs():
            job.request_cancel()
        deadline = None if timeout is None else time.monotonic() + timeout
        for t in self._threads:
            remaining = None if deadline is None else max(deadline - time.monotonic(), 0.1)
            t.join(remaining)
        if self._fleet is not None:
            self._fleet.stop()
