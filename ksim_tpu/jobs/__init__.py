"""Tenant job plane: queue + worker pool + per-job isolation planes.

See ksim_tpu/jobs/manager.py for the subsystem docstring, docs/jobs.md
for the API, queue semantics and tenancy model, and
ksim_tpu/jobs/fleet.py for the multi-worker fleet (lease-claimed jobs
over one shared journal)."""

from ksim_tpu.jobs.fleet import FileLock, FleetMember, JournalTailer, LeasePlane
from ksim_tpu.jobs.journal import JobJournal
from ksim_tpu.jobs.manager import (
    JOB_FAULT_SITES,
    TERMINAL_STATES,
    Job,
    JobLimitExceeded,
    JobManager,
    JobThrottled,
    parse_job_faults,
)
from ksim_tpu.jobs.queue import JobQueue, JobQueueFull

__all__ = [
    "JOB_FAULT_SITES",
    "TERMINAL_STATES",
    "FileLock",
    "FleetMember",
    "Job",
    "JobJournal",
    "JobLimitExceeded",
    "JobManager",
    "JobQueue",
    "JobQueueFull",
    "JobThrottled",
    "JournalTailer",
    "LeasePlane",
    "parse_job_faults",
]
