"""Tenant job plane: queue + worker pool + per-job isolation planes.

See ksim_tpu/jobs/manager.py for the subsystem docstring and
docs/jobs.md for the API, queue semantics and tenancy model."""

from ksim_tpu.jobs.journal import JobJournal
from ksim_tpu.jobs.manager import (
    JOB_FAULT_SITES,
    TERMINAL_STATES,
    Job,
    JobLimitExceeded,
    JobManager,
    JobThrottled,
    parse_job_faults,
)
from ksim_tpu.jobs.queue import JobQueue, JobQueueFull

__all__ = [
    "JOB_FAULT_SITES",
    "TERMINAL_STATES",
    "Job",
    "JobJournal",
    "JobLimitExceeded",
    "JobManager",
    "JobQueue",
    "JobQueueFull",
    "JobThrottled",
    "parse_job_faults",
]
