"""Crash-safe job journal: an append-only JSONL write-ahead log.

ROADMAP service round 3 (e): the job registry was in-memory only — a
server crash or restart silently forgot submitted specs, in-flight
progress and finished results.  This module makes the job plane durable
the way the reference's long-lived service is implicitly durable (it
holds no tenant jobs at all): every submission (the full KEP-140-ish
spec document), every state transition, every cancellation and every
result document is one checksummed JSONL record appended (and flushed)
to ``$KSIM_JOBS_DIR/jobs.journal.jsonl`` BEFORE the in-memory state
machine observes the transition.  On startup ``JobManager`` replays the
journal to reconstruct the registry (ksim_tpu/jobs/manager.py
``_recover``).

Record format — one line per record::

    {"crc": <crc32 of the canonical rec JSON>, "rec": {...}}

``rec`` is canonicalized (sorted keys, no whitespace) before the CRC so
the checksum is stable under re-serialization.  ``rec["t"]`` is the
record type:

- ``submit``: id, ordinal, priority, created, and ``doc`` — the raw
  submitted job document, verbatim;
- ``state``: id, state, optional error, ts;
- ``result``: id, and the full result document (served byte-identically
  after a restart);
- ``cancel``: id, ts (the cancel REQUEST; the resulting terminal state
  arrives as its own ``state`` record);
- ``checkpoint``: id, seq, cursor (committed step-key index), segment,
  and the exact-state restore payload — ``store``
  (``ClusterStore.checkpoint()``: objects verbatim + rv counter +
  mutation epoch) plus ``service`` (backoff / pass counter /
  featurizer slot order / pnts carries) and the partial ``result``
  accounting (docs/jobs.md "Incremental resume").  Appended by the job
  worker after committed segment reconciles, throttled by
  ``KSIM_JOBS_CHECKPOINT_EVERY``; ``KSIM_JOBS_RESUME=1`` restores from
  the NEWEST valid checkpoint and replays only the remaining suffix.
  The torn-tail rule already gives checkpoint fallback for free: a
  record torn mid-append truncates away, so recovery sees the previous
  intact checkpoint.

Recovery is torn-tail tolerant: a process killed mid-append leaves a
partial (or checksum-failing) final line, and ``replay`` truncates the
file at the last valid record instead of crashing — corruption can lose
the torn tail, never the journal.  Compaction (``maybe_compact``)
bounds the file: past ``KSIM_JOBS_JOURNAL_MAX_BYTES`` the live registry
is rewritten as a snapshot (atomic tmp-file + fsync + rename), dropping
records of jobs the retention policy already pruned and keeping only
the NEWEST checkpoint per live job (older checkpoints are dead weight
once a newer one is durable).

Multi-process sharing (round 20): a fleet puts SEVERAL processes on one
journal — the front door appends submits/cancels while workers append
state/checkpoint/result records for the jobs they lease (docs/jobs.md
"Multi-worker fleet").  Two rules make that safe.  First, every append
is ONE ``os.write`` on an ``O_APPEND`` descriptor opened per record, so
concurrent appenders can interleave only at record granularity — the
old buffered ``f.write`` could split a multi-MB checkpoint line across
write(2) calls and interleave mid-record.  Second, ``shared=True`` arms
an ``fcntl.flock`` sidecar (``<path>.lock``) taken around appends,
replay's truncate, and compaction; shared compaction folds the FILE's
own records (not just this process's registry, which cannot see the
other appenders' records) and skips entirely when the lock is
contended.  Appenders re-open the path per record, so the compaction
rename never strands a writer on the old inode.

The module is stdlib-only and jax-free: recovery must work in a fresh
process whose backend may be wedged (the whole point of restarting).
Fault sites ``jobs.journal_append`` / ``jobs.journal_replay``
(docs/faults.md) inject I/O errors here so ``make faults`` proves an
append failure fails the ONE job, never poisons the registry.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import threading
import zlib
from typing import Any, Callable, Iterable, Iterator

from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE

__all__ = ["JobJournal", "JOURNAL_NAME"]

JOURNAL_NAME = "jobs.journal.jsonl"

#: Default compaction bound (bytes) — ``KSIM_JOBS_JOURNAL_MAX_BYTES``.
_MAX_BYTES_DEFAULT = 16 * 1024 * 1024


def _canon(rec: dict) -> str:
    """The canonical JSON the checksum covers (stable under
    re-serialization: sorted keys, no whitespace)."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _line(rec: dict) -> str:
    body = _canon(rec)
    crc = zlib.crc32(body.encode()) & 0xFFFFFFFF
    return (
        json.dumps({"crc": crc, "rec": json.loads(body)},
                   sort_keys=True, separators=(",", ":"))
        + "\n"
    )


def _decode_line(line: str) -> "dict | None":
    """One journal line -> the validated rec, or None (torn/corrupt)."""
    if not line.endswith("\n"):
        return None  # torn tail: the append died mid-write
    try:
        wrapper = json.loads(line)
        rec = wrapper["rec"]
        crc = int(wrapper["crc"])
    except (ValueError, KeyError, TypeError):
        return None
    if not isinstance(rec, dict):
        return None
    if zlib.crc32(_canon(rec).encode()) & 0xFFFFFFFF != crc:
        return None
    return rec


#: Terminal job states, duplicated from ``manager.TERMINAL_STATES`` —
#: the journal must stay importable without the manager (and jax-free).
_TERMINAL = frozenset({"succeeded", "failed", "cancelled", "interrupted"})


def _fold_compact(recs: "list[dict]") -> "list[dict]":
    """Fold a full record stream into its compact equivalent: per job
    (first-submit order) the submit, the NEWEST state, the cancel
    request while live, and either the result (terminal) or the NEWEST
    checkpoint (live — older checkpoints are the bulk compaction
    exists to shed).  Record types this fold does not understand, and
    records for ids whose submit is absent, pass through verbatim at
    the end: a shared journal must never drop another appender's data
    it merely fails to recognize."""
    order: list[str] = []
    ents: dict[str, dict] = {}
    extras: list[dict] = []
    for rec in recs:
        t = rec.get("t")
        jid = rec.get("id")
        if t == "submit" and jid:
            ent = ents.get(jid)
            if ent is None:
                order.append(jid)
                ents[jid] = {"submit": rec, "state": None, "result": None,
                             "cancel": None, "checkpoint": None}
            else:
                ent["submit"] = rec
        elif t in ("state", "result", "cancel", "checkpoint") and jid in ents:
            key = "checkpoint" if t == "checkpoint" else t
            ents[jid][key] = rec  # newest wins
        else:
            extras.append(rec)
    out: list[dict] = []
    for jid in order:
        ent = ents[jid]
        out.append(ent["submit"])
        st = ent["state"]
        terminal = st is not None and st.get("state") in _TERMINAL
        if ent["cancel"] is not None and not terminal:
            out.append(ent["cancel"])
        if st is not None:
            out.append(st)
        if terminal:
            if ent["result"] is not None:
                out.append(ent["result"])
        elif ent["checkpoint"] is not None:
            out.append(ent["checkpoint"])
    out.extend(extras)
    return out


class JobJournal:
    """Append-only JSONL WAL for one JobManager's registry.

    Thread-safe: appends from the submit path and every worker thread
    serialize on ``_lock``.  Lock order: ``_lock`` comes FIRST —
    ``append``/``replay`` consult the fault plane under it, and
    ``maybe_compact`` invokes the manager's snapshot callable under it
    (journal ``_lock`` -> manager ``_lock`` -> job ``_cond``; the
    manager side of that chain is declared beside
    ``JobManager._journal_records``)."""

    # Machine-checked acquisition order (tools/ksimlint lock-order —
    # docs/lint.md "Lock order"): the fault plane, and the trace plane
    # it emits into, are leaves under the journal lock.
    # ksimlint: lock-order(JobJournal._lock<FaultPlane._lock)
    # ksimlint: lock-order(JobJournal._lock<TracePlane._lock)

    def __init__(self, path: str, *, max_bytes: "int | None" = None,
                 shared: bool = False) -> None:
        if max_bytes is None:
            raw = os.environ.get("KSIM_JOBS_JOURNAL_MAX_BYTES", "")
            max_bytes = int(raw) if raw else _MAX_BYTES_DEFAULT
        self.path = path
        self.max_bytes = max(int(max_bytes), 0)  # 0 = never compact
        #: True when OTHER processes may hold this journal open (fleet
        #: mode): appends/truncates/compactions take the flock sidecar.
        self.shared = bool(shared)
        self._lock_path = f"{path}.lock"
        self._lock = threading.Lock()
        self._size = 0  # guarded-by: _lock (local appends only in shared mode)
        self.appends = 0  # guarded-by: _lock
        self.append_errors = 0  # guarded-by: _lock
        self.compactions = 0  # guarded-by: _lock
        self.truncated_bytes = 0  # guarded-by: _lock (torn-tail recovery)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    @contextlib.contextmanager
    def _flock(self, *, blocking: bool = True) -> "Iterator[bool]":
        """Cross-PROCESS exclusion (fcntl.flock on the sidecar file);
        yields whether the lock was obtained.  A no-op yielding True
        when the journal is not shared — threads in one process already
        serialize on ``_lock``.  flock is per-open-description, so two
        handles in ONE process exclude each other too (what the
        in-process durability tests lean on)."""
        if not self.shared:
            yield True
            return
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            try:
                fcntl.flock(
                    fd, fcntl.LOCK_EX | (0 if blocking else fcntl.LOCK_NB))
            except OSError:
                yield False
                return
            try:
                yield True
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)

    # -- append ----------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Durably append one record (single ``os.write`` on an
        ``O_APPEND`` descriptor, then fsync).  The per-record open plus
        single write keeps concurrent appenders record-atomic: buffered
        I/O could split one large line across write(2) calls and let a
        second process interleave mid-record.  Raises on I/O failure
        (including the armed ``jobs.journal_append`` fault) — the
        CALLER owns the containment policy: fail the one job the record
        belongs to, never the registry."""
        data = _line(rec).encode("utf-8")
        with TRACE.span("jobs.journal_append", type=rec.get("t")):
            with self._lock:
                try:
                    FAULTS.check("jobs.journal_append")
                    with self._flock():
                        fd = os.open(
                            self.path,
                            os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
                        try:
                            # A short write can only come from the OS
                            # (disk full, signal); under the flock the
                            # retry tail still cannot interleave, and a
                            # crash between writes leaves a torn tail
                            # replay() truncates away.
                            view = memoryview(data)
                            while view:
                                view = view[os.write(fd, view):]
                            os.fsync(fd)
                        finally:
                            os.close(fd)
                except BaseException:
                    self.append_errors += 1
                    raise
                self._size += len(data)
                self.appends += 1

    # -- recovery --------------------------------------------------------

    def replay(self) -> list[dict]:
        """Read every valid record, truncating the file at the FIRST
        invalid line (a torn tail from a mid-append crash, or garbage —
        everything after it is unordered debris the WAL contract cannot
        vouch for).  Never raises on corruption; I/O errors (including
        the armed ``jobs.journal_replay`` fault) propagate to the
        manager, which recovers what it can and never crashes startup."""
        with TRACE.span("jobs.journal_replay"):
            with self._lock:
                FAULTS.check("jobs.journal_replay")
                # Shared mode holds the flock across read + truncate so
                # the torn-tail cut never races a live appender (whose
                # record past our read point would otherwise be cut).
                with self._flock():
                    recs: list[dict] = []
                    good_end = 0
                    try:
                        f = open(self.path, "r", encoding="utf-8",
                                 newline="")
                    except FileNotFoundError:
                        return recs
                    with f:
                        for line in f:
                            rec = _decode_line(line)
                            if rec is None:
                                break
                            recs.append(rec)
                            good_end += len(line.encode())
                        total = os.path.getsize(self.path)
                    if good_end < total:
                        self.truncated_bytes = total - good_end
                        with open(self.path, "a", encoding="utf-8") as tf:
                            tf.truncate(good_end)
                    self._size = good_end
                    return recs

    # -- compaction ------------------------------------------------------

    def maybe_compact(self, snapshot_fn: Callable[[], Iterable[dict]]) -> bool:  # ksimlint: thread-role(compactor)
        """Rewrite the journal as a snapshot of the LIVE registry when
        it outgrew ``max_bytes``.  ``snapshot_fn`` is called under the
        journal lock and must not take it again (the manager's registry
        lock is fine — see the class docstring's lock order).  Failures
        are swallowed: compaction is an optimization, the oversized
        journal stays fully valid.

        Shared journals IGNORE ``snapshot_fn`` and fold the file's own
        records instead: this process's registry cannot see records the
        other fleet processes appended, and a registry-only rewrite
        would silently drop them.  The fold runs under a NON-blocking
        flock — contention means another process is appending or
        already compacting, so we skip and let a later call retry."""
        with self._lock:
            if not self.max_bytes:
                return False
            if self.shared:
                return self._compact_shared_locked()
            if self._size <= self.max_bytes:
                return False
            try:
                lines = [_line(rec) for rec in snapshot_fn()]
                tmp = f"{self.path}.tmp{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.writelines(lines)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            except OSError:
                return False
            self._size = sum(len(ln) for ln in lines)
            self.compactions += 1
            return True

    def _compact_shared_locked(self) -> bool:  # ksimlint: lock-held(_lock)
        """Shared-mode compaction body (caller holds ``_lock``).  Size
        comes from the FILE — the local ``_size`` counts only this
        process's appends.  Holding the exclusive flock across
        read-fold-rewrite keeps the rename atomic w.r.t. every other
        appender (they re-open the path per record, so nobody writes
        to the dead inode afterwards)."""
        try:
            if os.path.getsize(self.path) <= self.max_bytes:
                return False
        except OSError:
            return False
        with self._flock(blocking=False) as held:
            if not held:
                return False
            try:
                recs: list[dict] = []
                with open(self.path, "r", encoding="utf-8",
                          newline="") as f:
                    for line in f:
                        rec = _decode_line(line)
                        if rec is None:
                            break
                        recs.append(rec)
                lines = [_line(rec) for rec in _fold_compact(recs)]
                tmp = f"{self.path}.tmp{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.writelines(lines)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            except OSError:
                return False
            self._size = sum(len(ln.encode()) for ln in lines)
            self.compactions += 1
            return True

    # -- evidence --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "size_bytes": self._size,
                "max_bytes": self.max_bytes,
                "shared": self.shared,
                "appends": self.appends,
                "append_errors": self.append_errors,
                "compactions": self.compactions,
                "truncated_bytes": self.truncated_bytes,
            }
