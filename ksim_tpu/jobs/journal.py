"""Crash-safe job journal: an append-only JSONL write-ahead log.

ROADMAP service round 3 (e): the job registry was in-memory only — a
server crash or restart silently forgot submitted specs, in-flight
progress and finished results.  This module makes the job plane durable
the way the reference's long-lived service is implicitly durable (it
holds no tenant jobs at all): every submission (the full KEP-140-ish
spec document), every state transition, every cancellation and every
result document is one checksummed JSONL record appended (and flushed)
to ``$KSIM_JOBS_DIR/jobs.journal.jsonl`` BEFORE the in-memory state
machine observes the transition.  On startup ``JobManager`` replays the
journal to reconstruct the registry (ksim_tpu/jobs/manager.py
``_recover``).

Record format — one line per record::

    {"crc": <crc32 of the canonical rec JSON>, "rec": {...}}

``rec`` is canonicalized (sorted keys, no whitespace) before the CRC so
the checksum is stable under re-serialization.  ``rec["t"]`` is the
record type:

- ``submit``: id, ordinal, priority, created, and ``doc`` — the raw
  submitted job document, verbatim;
- ``state``: id, state, optional error, ts;
- ``result``: id, and the full result document (served byte-identically
  after a restart);
- ``cancel``: id, ts (the cancel REQUEST; the resulting terminal state
  arrives as its own ``state`` record);
- ``checkpoint``: id, seq, cursor (committed step-key index), segment,
  and the exact-state restore payload — ``store``
  (``ClusterStore.checkpoint()``: objects verbatim + rv counter +
  mutation epoch) plus ``service`` (backoff / pass counter /
  featurizer slot order / pnts carries) and the partial ``result``
  accounting (docs/jobs.md "Incremental resume").  Appended by the job
  worker after committed segment reconciles, throttled by
  ``KSIM_JOBS_CHECKPOINT_EVERY``; ``KSIM_JOBS_RESUME=1`` restores from
  the NEWEST valid checkpoint and replays only the remaining suffix.
  The torn-tail rule already gives checkpoint fallback for free: a
  record torn mid-append truncates away, so recovery sees the previous
  intact checkpoint.

Recovery is torn-tail tolerant: a process killed mid-append leaves a
partial (or checksum-failing) final line, and ``replay`` truncates the
file at the last valid record instead of crashing — corruption can lose
the torn tail, never the journal.  Compaction (``maybe_compact``)
bounds the file: past ``KSIM_JOBS_JOURNAL_MAX_BYTES`` the live registry
is rewritten as a snapshot (atomic tmp-file + fsync + rename), dropping
records of jobs the retention policy already pruned and keeping only
the NEWEST checkpoint per live job (older checkpoints are dead weight
once a newer one is durable).

The module is stdlib-only and jax-free: recovery must work in a fresh
process whose backend may be wedged (the whole point of restarting).
Fault sites ``jobs.journal_append`` / ``jobs.journal_replay``
(docs/faults.md) inject I/O errors here so ``make faults`` proves an
append failure fails the ONE job, never poisons the registry.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Callable, Iterable

from ksim_tpu.faults import FAULTS
from ksim_tpu.obs import TRACE

__all__ = ["JobJournal", "JOURNAL_NAME"]

JOURNAL_NAME = "jobs.journal.jsonl"

#: Default compaction bound (bytes) — ``KSIM_JOBS_JOURNAL_MAX_BYTES``.
_MAX_BYTES_DEFAULT = 16 * 1024 * 1024


def _canon(rec: dict) -> str:
    """The canonical JSON the checksum covers (stable under
    re-serialization: sorted keys, no whitespace)."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


def _line(rec: dict) -> str:
    body = _canon(rec)
    crc = zlib.crc32(body.encode()) & 0xFFFFFFFF
    return (
        json.dumps({"crc": crc, "rec": json.loads(body)},
                   sort_keys=True, separators=(",", ":"))
        + "\n"
    )


def _decode_line(line: str) -> "dict | None":
    """One journal line -> the validated rec, or None (torn/corrupt)."""
    if not line.endswith("\n"):
        return None  # torn tail: the append died mid-write
    try:
        wrapper = json.loads(line)
        rec = wrapper["rec"]
        crc = int(wrapper["crc"])
    except (ValueError, KeyError, TypeError):
        return None
    if not isinstance(rec, dict):
        return None
    if zlib.crc32(_canon(rec).encode()) & 0xFFFFFFFF != crc:
        return None
    return rec


class JobJournal:
    """Append-only JSONL WAL for one JobManager's registry.

    Thread-safe: appends from the submit path and every worker thread
    serialize on ``_lock``.  Lock order: ``_lock`` comes FIRST —
    ``append``/``replay`` consult the fault plane under it, and
    ``maybe_compact`` invokes the manager's snapshot callable under it
    (journal ``_lock`` -> manager ``_lock`` -> job ``_cond``; the
    manager side of that chain is declared beside
    ``JobManager._journal_records``)."""

    # Machine-checked acquisition order (tools/ksimlint lock-order —
    # docs/lint.md "Lock order"): the fault plane, and the trace plane
    # it emits into, are leaves under the journal lock.
    # ksimlint: lock-order(JobJournal._lock<FaultPlane._lock)
    # ksimlint: lock-order(JobJournal._lock<TracePlane._lock)

    def __init__(self, path: str, *, max_bytes: "int | None" = None) -> None:
        if max_bytes is None:
            raw = os.environ.get("KSIM_JOBS_JOURNAL_MAX_BYTES", "")
            max_bytes = int(raw) if raw else _MAX_BYTES_DEFAULT
        self.path = path
        self.max_bytes = max(int(max_bytes), 0)  # 0 = never compact
        self._lock = threading.Lock()
        self._size = 0  # guarded-by: _lock
        self.appends = 0  # guarded-by: _lock
        self.append_errors = 0  # guarded-by: _lock
        self.compactions = 0  # guarded-by: _lock
        self.truncated_bytes = 0  # guarded-by: _lock (torn-tail recovery)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    # -- append ----------------------------------------------------------

    def append(self, rec: dict) -> None:
        """Durably append one record (write + flush + fsync).  Raises on
        I/O failure (including the armed ``jobs.journal_append`` fault)
        — the CALLER owns the containment policy: fail the one job the
        record belongs to, never the registry."""
        line = _line(rec)
        with TRACE.span("jobs.journal_append", type=rec.get("t")):
            with self._lock:
                try:
                    FAULTS.check("jobs.journal_append")
                    with open(self.path, "a", encoding="utf-8") as f:
                        f.write(line)
                        f.flush()
                        os.fsync(f.fileno())
                except BaseException:
                    self.append_errors += 1
                    raise
                self._size += len(line)
                self.appends += 1

    # -- recovery --------------------------------------------------------

    def replay(self) -> list[dict]:
        """Read every valid record, truncating the file at the FIRST
        invalid line (a torn tail from a mid-append crash, or garbage —
        everything after it is unordered debris the WAL contract cannot
        vouch for).  Never raises on corruption; I/O errors (including
        the armed ``jobs.journal_replay`` fault) propagate to the
        manager, which recovers what it can and never crashes startup."""
        with TRACE.span("jobs.journal_replay"):
            with self._lock:
                FAULTS.check("jobs.journal_replay")
                recs: list[dict] = []
                good_end = 0
                try:
                    f = open(self.path, "r", encoding="utf-8", newline="")
                except FileNotFoundError:
                    return recs
                with f:
                    for line in f:
                        rec = _decode_line(line)
                        if rec is None:
                            break
                        recs.append(rec)
                        good_end += len(line.encode())
                    total = os.path.getsize(self.path)
                if good_end < total:
                    self.truncated_bytes = total - good_end
                    with open(self.path, "a", encoding="utf-8") as tf:
                        tf.truncate(good_end)
                self._size = good_end
                return recs

    # -- compaction ------------------------------------------------------

    def maybe_compact(self, snapshot_fn: Callable[[], Iterable[dict]]) -> bool:  # ksimlint: thread-role(compactor)
        """Rewrite the journal as a snapshot of the LIVE registry when
        it outgrew ``max_bytes``.  ``snapshot_fn`` is called under the
        journal lock and must not take it again (the manager's registry
        lock is fine — see the class docstring's lock order).  Failures
        are swallowed: compaction is an optimization, the oversized
        journal stays fully valid."""
        with self._lock:
            if not self.max_bytes or self._size <= self.max_bytes:
                return False
            try:
                lines = [_line(rec) for rec in snapshot_fn()]
                tmp = f"{self.path}.tmp{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.writelines(lines)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            except OSError:
                return False
            self._size = sum(len(ln) for ln in lines)
            self.compactions += 1
            return True

    # -- evidence --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "size_bytes": self._size,
                "max_bytes": self.max_bytes,
                "appends": self.appends,
                "append_errors": self.append_errors,
                "compactions": self.compactions,
                "truncated_bytes": self.truncated_bytes,
            }
