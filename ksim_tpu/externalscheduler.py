"""Deprecated external-scheduler SDK (compat shim).

The reference keeps ``pkg/externalscheduler`` as a deprecated older
surface next to ``pkg/debuggablescheduler`` (reference
simulator/pkg/externalscheduler/external_scheduler.go:39 deprecation
note).  This module mirrors that arrangement: the same capabilities,
re-exported under the old names, emitting DeprecationWarning.  New code
uses ksim_tpu.scheduler.service / ksim_tpu.cmd.scheduler directly.
"""

from __future__ import annotations

import warnings

from ksim_tpu.scheduler.profile import Builder  # noqa: F401 (compat)
from ksim_tpu.scheduler.service import SchedulerService


def new_scheduler(store, *, config=None, registry=None, **kw) -> SchedulerService:
    """Deprecated: construct the debuggable scheduler service (the
    reference's externalscheduler.NewSchedulerCommand analogue)."""
    warnings.warn(
        "ksim_tpu.externalscheduler is deprecated; use "
        "ksim_tpu.scheduler.service.SchedulerService",
        DeprecationWarning,
        stacklevel=2,
    )
    return SchedulerService(store, config=config, registry=registry, **kw)
