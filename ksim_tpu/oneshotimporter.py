"""One-shot importer: boot-time cluster replication.

Snap the source cluster through its snapshot service and Load into the
simulator, ignoring per-object errors and any scheduler configuration —
exactly the reference's flow (reference
simulator/oneshotimporter/importer.go:17-59: Snap from the export service,
convert, Load with IgnoreErr + IgnoreSchedulerConfiguration)."""

from __future__ import annotations

from typing import Protocol

from ksim_tpu.state.resources import JSON


class ReplicateService(Protocol):
    """What the importer needs from both sides (SnapshotService shape)."""

    def snap(self, label_selector: JSON | None = None) -> JSON: ...

    def load(self, resources: JSON, *, ignore_err: bool = False,
             ignore_scheduler_configuration: bool = False) -> None: ...


class OneShotImporter:
    def __init__(
        self, import_service: ReplicateService, export_service: ReplicateService
    ) -> None:
        self._import = import_service  # into the simulator
        self._export = export_service  # from the source cluster

    def import_cluster_resources(self, label_selector: JSON | None = None) -> None:
        """Snap the source, load into the simulator.  Scheduler config is
        never taken from the source (importer.go:44-59 note)."""
        resources = self._export.snap(label_selector)
        self._import.load(
            resources, ignore_err=True, ignore_scheduler_configuration=True
        )
