"""Process-global trace plane: spans, latency histograms, event ring.

The reference simulator's observability is the upstream scheduler's
Prometheus metrics plus klog (SURVEY §5); before this module the repo's
analogue was a mean-only ``Metrics`` counter/timer and scattered ad-hoc
dicts (``ReplayDriver.stats()``, ``FaultPlane`` site counters).  None of
it could answer the ROADMAP's open TPU wall-clock question — *where*
does the 50k trajectory spend its time, and *when* did a degradation
(fallback, watchdog timeout, breaker trip) actually happen.

This module is the single answer surface:

- **Spans** — named intervals on a monotonic clock (``TRACE.span``),
  one per pipeline phase (segment lower / dispatch / reconcile, the
  per-pass host step, write-back pushes, kubeapi requests).  Every span
  lands its duration in a fixed-bucket log-spaced latency histogram and
  (ring mode) a structured record in the event ring.
- **Events** — instants (``TRACE.event``): fallback reasons with the
  segment context, pass outcomes, fault-plane fires, breaker state
  changes, store-transaction commit/rollback.
- **Export** — the ring renders as Chrome trace-event JSON
  (``chrome://tracing`` / https://ui.perfetto.dev): spans become ``X``
  complete events nested per thread, instants become ``i`` events.
  ``KSIM_TRACE_OUT=path`` arms an atexit export, so any entrypoint can
  be traced from the environment alone; ``/api/v1/trace`` serves the
  same document live.

Observability is zero-perturbation by construction: nothing here reads
or writes scheduling state, so the churn behavior locks (repo
CLAUDE.md) hold byte-identically with tracing fully enabled —
tests/test_behavior_locks.py pins that.  With the plane fully disabled
every site costs ONE attribute check (``TRACE._active``) and nothing
else; the module is stdlib-only and never imports jax at module scope
(the optional ``jax.profiler.TraceAnnotation`` bridge is lazy and
guarded, so host spans can be correlated with device timelines when a
jax profile is being captured: ``KSIM_TRACE_JAX=1``).

Environment:

- ``KSIM_TRACE_OUT=path``  enable timing + ring; export Chrome trace
  JSON to ``path`` at process exit (and on demand).
- ``KSIM_TRACE=1``         enable timing + ring without a file.
- ``KSIM_TRACE=timing``    histograms/counters only (no ring storage).
- ``KSIM_TRACE_RING=N``    ring capacity (default 65536 records).
- ``KSIM_TRACE_JAX=1``     also wrap spans in
  ``jax.profiler.TraceAnnotation`` (guarded; no-op if jax is absent or
  no profiler session is active).

The span/event name taxonomy lives in ``SPAN_NAMES`` / ``EVENT_NAMES``
below; tests/test_obs.py's registry-sync test asserts every
``faults.py`` injection site and every replay fallback reason stays
covered (see docs/observability.md for the full table).
"""

from __future__ import annotations

import atexit
import bisect
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator

__all__ = [
    "TRACE",
    "TracePlane",
    "LatencyHistogram",
    "SPAN_NAMES",
    "EVENT_NAMES",
    "METRIC_NAMES",
    "OBS_DIR",
    "register_provider",
    "provider_snapshots",
    "process_identity",
    "publish_snapshot",
    "read_fleet_snapshots",
    "read_fleet_traces",
    "merge_latency_snapshots",
    "merge_fleet_docs",
    "merge_chrome_traces",
    "render_prometheus",
    "parse_prometheus",
]

# ---------------------------------------------------------------------------
# Taxonomy (docs/observability.md keeps the prose table in sync)
# ---------------------------------------------------------------------------

#: Interval (span) names.  The fault-plane injection sites
#: (faults.SITES) each fire INSIDE the span of the same name, so a
#: fault event always has an enclosing phase on the timeline.
SPAN_NAMES: tuple[str, ...] = (
    "replay.lower",  # segment lowering (engine/replay.py)
    "replay.prelower",  # NEXT window's speculative store-independent
    #                     prefix, overlapped with the in-flight dispatch
    #                     (runs on the main thread INSIDE the dispatch
    #                     span's wall-clock window — the two are
    #                     concurrent by design, not additive)
    "replay.dispatch",  # device dispatch incl. watchdog wait
    "replay.reconcile",  # staged store reconcile (the segment txn)
    "runner.step",  # one per-pass host step (ops + flush + schedule)
    "service.schedule",  # one scheduling pass (scheduler/service.py)
    "writeback.push",  # live-cluster write-back push
    "kubeapi.request",  # any kube-apiserver HTTP request
    "jobs.run",  # one tenant job end-to-end on a job-plane worker
    #              (ksim_tpu/jobs/manager.py; recorded on the JOB's
    #              private plane via the worker's scoped override)
    "scenario.ingest",  # one trace ingestion: parse + resample +
    #                     compile of a real cluster trace into the
    #                     operation stream (ksim_tpu/traces/compile.py;
    #                     args carry format/records/ops)
    "jobs.journal_append",  # one durable append to the job journal
    #                         (ksim_tpu/jobs/journal.py; the write-ahead
    #                         record behind every submission/transition)
    "jobs.journal_replay",  # one startup journal replay: scan + torn-
    #                         tail truncation + registry reconstruction
    "jobs.checkpoint_append",  # one segment-checkpoint record built and
    #                            durably appended to the job journal
    #                            (ksim_tpu/jobs/manager.py; wraps the
    #                            nested jobs.journal_append span)
    "jobs.checkpoint_restore",  # one restore attempt from a journaled
    #                             checkpoint: store + service carries
    #                             reconstructed on the worker thread
    #                             before the suffix replay
    "jobs.lease_claim",  # one fleet claim attempt: fold the lease file
    #                      under the exclusive flock, decide, append
    #                      (ksim_tpu/jobs/fleet.py; refusals return
    #                      inside the span without a claim record)
    "jobs.lease_renew",  # one heartbeat batch renewing this worker's
    #                      live leases (args.n — a missed batch is
    #                      survivable until lease expiry)
    "obs.publish",  # one crash-atomic telemetry snapshot written to
    #                 KSIM_JOBS_DIR/obs/<worker_id>.json (the fleet
    #                 observability plane's per-worker publish —
    #                 publish_snapshot below)
    "obs.fleet_merge",  # one fleet-scope aggregation: fold every
    #                     worker's published snapshot (or Chrome trace)
    #                     into the merged document (merge_fleet_docs /
    #                     merge_chrome_traces below)
    "traces.stream",  # one streaming trace ingestion on the producer
    #                   thread: parse + bounded-memory select + windowed
    #                   compile feeding the replay executor
    #                   (ksim_tpu/traces/stream.py; args carry
    #                   format/windows/ops — overlaps the replay it
    #                   feeds by construction)
)

#: Instant event names.
EVENT_NAMES: tuple[str, ...] = (
    "replay.fallback",  # segment rejected/degraded; args.reason is the
    #                     stable histogram reason (ReplayDriver._reject)
    "replay.watchdog_timeout",  # a dispatch exceeded the watchdog
    "replay.breaker_open",  # the circuit breaker tripped (args.cause:
    #                         device_error / reconcile_fault /
    #                         probe_failed — the last is a half-open
    #                         probe that failed and re-opened with a
    #                         doubled cooldown)
    "service.pass",  # pass outcome: attempts/scheduled/unschedulable
    "fault.fired",  # the fault plane injected at args.site
    "store.txn_commit",  # segment transaction committed (args.writes)
    "store.txn_rollback",  # segment transaction rolled back
    "replay.cache_invalidate",  # the lowered-universe cache flushed
    #                             (args.reason: fallback / rollback /
    #                             epoch_mismatch / epoch_raced /
    #                             sched_config / no_plan)
    "replay.fleet_lane_fallback",  # one fleet lane left the convergent
    #                                cohort (args.lane, args.reason) and
    #                                continues on the solo device path
    #                                (engine/fleet.py)
    "jobs.enqueue",  # a tenant job entered the job queue (args.job,
    #                  args.priority — ksim_tpu/jobs/manager.py)
    "job.cancelled",  # a tenant job was cancelled (queued or mid-run;
    #                   mid-segment cancellation rolls the in-flight
    #                   segment transaction back first)
    "replay.breaker_probe",  # the open breaker's cooldown elapsed and
    #                          ONE probe segment was admitted to the
    #                          device path (half-open state)
    "replay.breaker_close",  # a probe dispatch came back healthy: the
    #                          breaker closed and the driver re-promoted
    #                          to the device path
    "compilecache.evict",  # an on-disk serialized executable was
    #                        discarded (args.reason: corrupt /
    #                        key_mismatch / deserialize_failed /
    #                        exec_failed — engine/compilecache.py)
    "jobs.journal_recover",  # startup journal replay reconstructed the
    #                          job registry (args: jobs / interrupted /
    #                          resumed / truncated_bytes)
    "jobs.checkpoint",  # segment-checkpoint cadence outcome: written
    #                     (args: job / segment / cursor / bytes) or
    #                     skipped (args.skipped=True, args.reason:
    #                     max_bytes / waiting_pods / append_failed —
    #                     a skip never fails the job)
    "jobs.checkpoint_restore",  # restore-from-checkpoint outcome
    #                             (args.restored True/False; a failed
    #                             attempt falls back to the previous
    #                             checkpoint, then to scratch)
    "jobs.fleet_claim",  # a fleet member won a job lease (args: job /
    #                      worker / epoch / takeover — takeover=True is
    #                      the fail-over path re-claiming an expired
    #                      lease; ksim_tpu/jobs/fleet.py)
    "jobs.lease_expired",  # a lease aged out un-renewed and a survivor
    #                        took the job over (args: job / worker — the
    #                        DEAD owner being charged — / epoch)
    "obs.snapshot_stale",  # fleet aggregation found a worker snapshot
    #                        older than its publish cadence allows
    #                        (args: worker / stale_s — the dead worker
    #                        is FLAGGED in the merged doc, never
    #                        silently dropped)
    "traces.ingest_fallback",  # the streaming producer degraded to the
    #                            materialized ingest path (args.reason —
    #                            an armed fault or unexpected error
    #                            before the first window; counts stay
    #                            byte-identical, only the O(window)
    #                            memory claim is forfeited for this run)
)

_KNOWN_NAMES = frozenset(SPAN_NAMES) | frozenset(EVENT_NAMES)

#: Prometheus exposition metric FAMILY names (``GET /metrics``).  Like
#: SPAN_NAMES/EVENT_NAMES this is a machine-checked registry: the
#: registry-literals lint rule asserts every ``_expo_family("...")``
#: literal below is registered here and every entry here is spelled at
#: exactly such a call site (docs/lint.md "Registry literals").
#: Individual counter/timer/site names become LABELS (``name`` /
#: ``site``), not families, so the family set stays a static literal.
METRIC_NAMES: tuple[str, ...] = (
    "ksim_counter_total",
    "ksim_event_total",
    "ksim_fault_calls_total",
    "ksim_fault_fired_total",
    "ksim_latency_seconds",
    "ksim_queue_depth",
    "ksim_queue_capacity",
    "ksim_workers_pool",
    "ksim_workers_active",
    "ksim_breaker_open",
    "ksim_uptime_seconds",
    "ksim_snapshot_age_seconds",
    "ksim_up",
    "ksim_trace_ring_evicted_total",
)


def _expo_family(name: str, kind: str, help_: str) -> dict:
    """Declare one exposition family.  The first argument MUST be a
    string literal — the registry-literals rule scans these calls the
    same way it scans ``TRACE.span("...")`` sites."""
    return {"name": name, "kind": kind, "help": help_}


#: The exposition surface, in render order.  ``kind`` is the Prometheus
#: TYPE; histogram families render ``_bucket``/``_sum``/``_count``
#: samples with ``le`` labels from the fixed LatencyHistogram edges.
_EXPO_FAMILIES: tuple[dict, ...] = (
    _expo_family(
        "ksim_counter_total", "counter",
        "Scheduler counters (label: name).",
    ),
    _expo_family(
        "ksim_event_total", "counter",
        "Trace-plane instant events (label: name).",
    ),
    _expo_family(
        "ksim_fault_calls_total", "counter",
        "Fault-plane site traversals (label: site).",
    ),
    _expo_family(
        "ksim_fault_fired_total", "counter",
        "Fault-plane injections fired (label: site).",
    ),
    _expo_family(
        "ksim_latency_seconds", "histogram",
        "Latency histograms over the fixed log-spaced edges "
        "(label: site = span or timer name).",
    ),
    _expo_family("ksim_queue_depth", "gauge", "Job queue depth."),
    _expo_family("ksim_queue_capacity", "gauge", "Job queue capacity."),
    _expo_family("ksim_workers_pool", "gauge", "Local worker pool size."),
    _expo_family(
        "ksim_workers_active", "gauge", "Local workers running a job.",
    ),
    _expo_family(
        "ksim_breaker_open", "gauge",
        "Replay circuit breaker state (1 = open).",
    ),
    _expo_family("ksim_uptime_seconds", "gauge", "Process uptime."),
    _expo_family(
        "ksim_snapshot_age_seconds", "gauge",
        "Age of a worker's published snapshot (fleet scope).",
    ),
    _expo_family(
        "ksim_up", "gauge", "1 = snapshot fresh, 0 = stale.",
    ),
    _expo_family(
        "ksim_trace_ring_evicted_total", "counter",
        "Trace ring records evicted.",
    ),
)


# ---------------------------------------------------------------------------
# Latency histogram
# ---------------------------------------------------------------------------


def _log_edges() -> tuple[float, ...]:
    """Fixed log-spaced bucket upper edges: 4 per decade from 1 µs to
    100 s (33 edges; an overflow bucket catches the rest).  Fixed — not
    adaptive — so two snapshots (or two processes) always merge and
    compare bucket-for-bucket."""
    return tuple(1e-6 * 10 ** (i / 4) for i in range(33))


class LatencyHistogram:
    """Fixed-bucket latency histogram (seconds).  NOT thread-safe on its
    own — callers (``TracePlane``, ``util.Metrics``) hold their lock."""

    EDGES: tuple[float, ...] = _log_edges()

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self) -> None:
        self.counts = [0] * (len(self.EDGES) + 1)  # +1 = overflow
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = 0.0

    def observe(self, seconds: float) -> None:
        # bisect_left: an observation exactly ON an edge belongs to the
        # bucket whose upper edge it is (le semantics, like Prometheus).
        self.counts[bisect.bisect_left(self.EDGES, seconds)] += 1
        self.count += 1
        self.total += seconds
        if seconds < self.vmin:
            self.vmin = seconds
        if seconds > self.vmax:
            self.vmax = seconds

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (upper edge of the
        bucket holding the q-th observation; the overflow bucket
        reports the observed max)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                # Clamped: a bucket's upper edge can exceed anything
                # actually observed.
                return (
                    min(self.EDGES[i], self.vmax)
                    if i < len(self.EDGES)
                    else self.vmax
                )
        return self.vmax

    def snapshot(self) -> dict:
        """JSON-ready view.  Keeps the legacy mean-only timer keys
        (``total_seconds`` / ``count`` / ``mean_seconds`` — pinned by
        tests/test_server.py) and adds the histogram: nonzero buckets
        as ``[upper_edge_seconds, count]`` pairs plus estimated
        quantiles."""
        if not self.count:
            return {"count": 0, "total_seconds": 0.0, "mean_seconds": 0.0}
        buckets = [
            [round(self.EDGES[i], 9) if i < len(self.EDGES) else None, c]
            for i, c in enumerate(self.counts)
            if c
        ]
        return {
            "count": self.count,
            "total_seconds": round(self.total, 6),
            "mean_seconds": round(self.total / self.count, 6),
            "min_seconds": round(self.vmin, 6),
            "max_seconds": round(self.vmax, 6),
            "p50_seconds": round(self.quantile(0.50), 6),
            "p90_seconds": round(self.quantile(0.90), 6),
            "p99_seconds": round(self.quantile(0.99), 6),
            "buckets": buckets,
        }

    def merge_snapshot(self, snap: dict) -> None:
        """Fold one ``snapshot()`` document into this histogram,
        bucket-for-bucket.  EXACT by construction: the edges are fixed
        (never adaptive), so two snapshots' buckets are the same
        partition of the real line and addition loses nothing — the
        merged quantiles are as honest as solo ones.  A bucket edge
        that is not one of ours means the snapshot came from a
        different (future?) edge layout: fail loudly rather than fold
        counts into the wrong bucket."""
        count = int(snap.get("count") or 0)
        if count <= 0:
            return
        for edge, c in snap.get("buckets") or ():
            if edge is None:
                i = len(self.EDGES)
            else:
                i = _EDGE_INDEX.get(edge)
                if i is None:
                    raise ValueError(
                        f"snapshot bucket edge {edge!r} is not one of the "
                        f"fixed histogram edges"
                    )
            self.counts[i] += int(c)
        self.count += count
        self.total += float(snap.get("total_seconds") or 0.0)
        vmin = snap.get("min_seconds")
        if vmin is not None and float(vmin) < self.vmin:
            self.vmin = float(vmin)
        vmax = snap.get("max_seconds")
        if vmax is not None and float(vmax) > self.vmax:
            self.vmax = float(vmax)

    @classmethod
    def from_snapshot(cls, snap: dict) -> "LatencyHistogram":
        h = cls()
        h.merge_snapshot(snap)
        return h


#: Serialized-edge -> bucket index (snapshots round edges to 9 digits;
#: JSON round-trips floats exactly, so dict lookup is safe).
_EDGE_INDEX: dict[float, int] = {
    round(e, 9): i for i, e in enumerate(LatencyHistogram.EDGES)
}


def merge_latency_snapshots(snaps: "list[dict]") -> dict:
    """Bucket-wise merge of K ``LatencyHistogram.snapshot()`` documents
    into one merged snapshot (the fleet aggregation primitive; the
    property test in tests/test_obs_fleet.py pins merge == histogram of
    the concatenated observations)."""
    h = LatencyHistogram()
    for snap in snaps:
        h.merge_snapshot(snap)
    return h.snapshot()


# ---------------------------------------------------------------------------
# The plane
# ---------------------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing context manager — the whole disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    """One live span.  Records at EXIT: a span that never exits (a
    wedged dispatch abandoned with its watchdog worker) simply leaves
    no record — the caller-side watchdog timeout event is the evidence
    for that case."""

    __slots__ = ("_plane", "name", "args", "_t0", "_jax_ctx")

    def __init__(self, plane: "TracePlane", name: str, args: dict) -> None:
        self._plane = plane
        self.name = name
        self.args = args
        self._t0 = 0
        self._jax_ctx = None

    def __enter__(self):
        plane = self._plane
        tl = plane._tls
        tl.depth = getattr(tl, "depth", 0) + 1
        if plane._jax_bridge:
            # Guarded device-timeline bridge: annotations show up in a
            # captured jax profile next to the XLA ops they enclose.
            try:
                import jax

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        self._t0 = time.perf_counter_ns()
        return self

    def set(self, **args) -> None:
        """Refine span attributes mid-flight (recorded at exit) — for
        values the caller only learns inside the span, e.g. the ACTUAL
        lowered step count of a window that hit a vocabulary miss."""
        self.args.update(args)

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        plane = self._plane
        tl = plane._tls
        depth = getattr(tl, "depth", 1)
        tl.depth = depth - 1
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        plane._record_span(self.name, self._t0, t1, depth - 1, self.args)
        return False


class _PlaneScope:
    """Context manager installing an override plane for the current
    thread (``TracePlane.scoped``); restores the previous override on
    exit, so scopes nest."""

    __slots__ = ("_plane", "_override", "_prev")

    def __init__(self, plane: "TracePlane", override: "TracePlane | None") -> None:
        self._plane = plane
        self._override = override
        self._prev = None

    def __enter__(self):
        tls = self._plane._tls
        self._prev = getattr(tls, "scope", None)
        tls.scope = self._override
        return self._override

    def __exit__(self, *exc):
        self._plane._tls.scope = self._prev
        return False


class TracePlane:
    """Bounded, thread-safe trace storage — instance-scoped since
    round 13 (the job plane), with the process-global ``TRACE`` as the
    default instance.

    Three independently useful layers, one ``_active`` gate:

    - per-name latency histograms + event counters (``timing``),
    - the structured event ring (``ring``),
    - the Chrome-trace exporter over the ring.

    Thread-safe: spans/events land from the scheduler watch loop, the
    write-back thread, HTTP handler threads, and the replay dispatch
    worker concurrently; one leaf lock guards all storage (nothing
    under it calls out, so it cannot participate in a lock cycle).

    **Scoped override** (multi-tenancy): ``TRACE.scoped(plane)``
    installs ``plane`` as the CURRENT THREAD's recording target — every
    ``span``/``event``/``ensure_timing``/``phase_totals`` call on the
    default plane delegates to it until the scope exits.  Call sites
    keep addressing the module-global ``TRACE``; a tenant-job worker
    (ksim_tpu/jobs) wraps its run in a scope and gets a private ring,
    private histograms, and per-record ``tags`` (e.g. ``job=<id>``)
    without a single call-site change.  The replay executor propagates
    the scope onto its watchdogged dispatch worker
    (engine/replay.py ``_run_watchdogged``), so spans/events emitted
    there stay attributed to the owning job.  Reads of a SPECIFIC
    plane's storage (``snapshot``/``ring_records``/``export_chrome``)
    never delegate — an HTTP handler asking the global plane gets the
    global plane.

    ``tags`` merge into every recorded span/event's args (the job id on
    every record); ``sink`` — set via ``set_sink`` — receives each
    record dict AFTER the storage lock is released (it may fan records
    into an SSE stream; a raising sink is swallowed)."""

    def __init__(self, *, tags: "dict | None" = None) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._active = False
        # Set by an explicit disable() / KSIM_TRACE=off: ensure_timing's
        # convenience activation must never override an operator's
        # stated choice.
        self._user_disabled = False
        self._ring_on = False  # guarded-by: _lock
        self._jax_bridge = False
        self.out_path: str | None = None
        # Constant after construction (read-only on the hot path, so no
        # lock): args merged into every record, and the out-of-lock
        # record callback.
        self._tags: dict = dict(tags or {})
        self._sink: "Callable[[dict], None] | None" = None
        self._epoch_ns = time.perf_counter_ns()  # guarded-by: _lock
        self._hist: dict[str, LatencyHistogram] = {}  # guarded-by: _lock
        self._counters: dict[str, int] = {}  # guarded-by: _lock
        self._ring: deque = deque(maxlen=65536)  # guarded-by: _lock
        # guarded-by: _lock (ring pressure evidence: dropped = appended - len)
        self._appended = 0
        self._thread_names: dict[int, str] = {}  # guarded-by: _lock

    # -- configuration ---------------------------------------------------

    def enable(self, *, ring: bool = True, out: str | None = None) -> None:
        """Turn the plane on.  ``ring=False`` keeps histograms/counters
        only (no per-record storage); ``out`` arms the atexit Chrome
        export (also settable via ``KSIM_TRACE_OUT``)."""
        with self._lock:
            self._ring_on = ring or out is not None
            if out is not None:
                self.out_path = out
            self._user_disabled = False
            self._active = True

    def disable(self) -> None:
        """One attribute check per site from here on (storage kept;
        ``reset`` clears it).  Sticky against ``ensure_timing``: only an
        explicit ``enable`` turns the plane back on."""
        self._active = False
        self._user_disabled = True

    def reset(self) -> None:
        """Drop all recorded state (test teardown); enablement flags
        and the ring capacity survive."""
        with self._lock:
            self._hist.clear()
            self._counters.clear()
            self._ring.clear()
            self._appended = 0
            self._thread_names.clear()
            self._epoch_ns = time.perf_counter_ns()

    def configure_from_env(self, environ=os.environ) -> None:
        """Apply ``KSIM_TRACE*`` (import-time; tests re-invoke)."""
        cap = environ.get("KSIM_TRACE_RING", "")
        if cap:
            try:
                maxlen = max(int(cap), 16)
            except ValueError:
                maxlen = None
            if maxlen is not None:
                # Swap under the lock: a concurrent event() append must
                # never land in an orphaned deque (that record would
                # vanish and the eviction accounting would over-report).
                with self._lock:
                    self._ring = deque(self._ring, maxlen=maxlen)
        self._jax_bridge = environ.get("KSIM_TRACE_JAX", "") == "1"
        out = environ.get("KSIM_TRACE_OUT", "")
        mode = environ.get("KSIM_TRACE", "")
        if mode in ("0", "off"):
            # The operator's opt-out beats everything, including a
            # KSIM_TRACE_OUT a wrapper script may have exported — the
            # same never-override-a-stated-choice contract as
            # ensure_timing vs disable().
            self.disable()
        elif out:
            self.enable(ring=True, out=out)
        elif mode:
            self.enable(ring=(mode != "timing"))

    @property
    def active(self) -> bool:
        return self._active

    def set_sink(self, sink: "Callable[[dict], None] | None") -> None:
        """Install (or clear) the record callback.  Set before the plane
        starts receiving records — the hot path reads it unlocked."""
        self._sink = sink

    # -- scoped override -------------------------------------------------

    def scoped(self, plane: "TracePlane | None") -> _PlaneScope:
        """Install ``plane`` as the current thread's recording target
        for ``span``/``event``/``ensure_timing``/``phase_totals`` calls
        on THIS plane (``None`` = a no-op scope).  Used by the job plane
        to give each tenant job a private trace plane without changing
        any call site; the previous scope restores on exit."""
        return _PlaneScope(self, plane)

    def scope(self) -> "TracePlane | None":
        """The current thread's override plane, if any — captured by the
        replay executor before handing work to its dispatch worker so
        the scope survives the thread hop."""
        return getattr(self._tls, "scope", None)

    def scope_tags(self) -> dict:
        """The effective record tags for the calling thread (the
        override plane's, else this plane's) — e.g. the owning job id
        for the compile cache's per-tenant sharing evidence."""
        ov = getattr(self._tls, "scope", None)
        return (ov if ov is not None else self)._tags

    def ensure_timing(self) -> None:
        """Idempotent timing-only activation.  ScenarioRunner calls this
        so per-phase wall-clock totals always exist (the histogram cost
        is two clock reads + one locked increment per span, at
        segment/pass granularity); ring storage stays off unless the
        operator armed it, and an explicit ``disable()`` /
        ``KSIM_TRACE=off`` wins — convenience activation never
        overrides a stated opt-out."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            ov.ensure_timing()
            return
        if not self._active and not self._user_disabled:
            self.enable(ring=False)

    # -- the hot path ----------------------------------------------------

    def span(self, name: str, **args):
        """Open a named span; a no-op singleton when the plane is off
        (the disabled path is one TLS read + one attribute check).  A
        thread-scoped override plane (``scoped``) takes the record
        instead."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            return ov.span(name, **args)
        if not self._active:
            return _NOOP
        return _Span(self, name, args)

    def event(self, name: str, **args) -> None:
        """Record one instant event (counted always; stored when the
        ring is on)."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            ov.event(name, **args)
            return
        if not self._active:
            return
        now = time.perf_counter_ns()
        tid = threading.get_ident()
        if self._tags:
            args = {**self._tags, **args}
        sink = self._sink
        rec = None
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + 1
            if self._ring_on or sink is not None:
                rec = {"ph": "i", "name": name, "t": now, "tid": tid, "args": args}
                if self._ring_on:
                    self._note_thread(tid)
                    self._appended += 1
                    self._ring.append(rec)
        if rec is not None and sink is not None:
            try:
                sink(rec)
            except Exception:  # a broken sink must not break the plane
                pass

    def _record_span(
        self, name: str, t0: int, t1: int, depth: int, args: dict
    ) -> None:
        tid = threading.get_ident()
        if self._tags:
            args = {**self._tags, **args}
        sink = self._sink
        rec = None
        with self._lock:
            hist = self._hist.get(name)
            if hist is None:
                hist = self._hist[name] = LatencyHistogram()
            hist.observe((t1 - t0) / 1e9)
            if self._ring_on or sink is not None:
                rec = {
                    "ph": "X",
                    "name": name,
                    "t": t0,
                    "d": t1 - t0,
                    "tid": tid,
                    "depth": depth,
                    "args": args,
                }
                if self._ring_on:
                    self._note_thread(tid)
                    self._appended += 1
                    self._ring.append(rec)
        if rec is not None and sink is not None:
            try:
                sink(rec)
            except Exception:  # a broken sink must not break the plane
                pass

    def _note_thread(self, tid: int) -> None:  # ksimlint: lock-held(_lock)
        if tid not in self._thread_names:
            t = threading.current_thread()
            self._thread_names[tid] = t.name

    # -- evidence --------------------------------------------------------

    def phase_totals(self) -> dict[str, tuple[float, int]]:
        """Per-span-name ``(total_seconds, count)`` — the runner diffs
        two of these around a run for its per-phase breakdown.  Follows
        the thread's scoped override, so a job-scoped run's phase split
        reads the JOB's histograms."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            return ov.phase_totals()
        with self._lock:
            return {n: (h.total, h.count) for n, h in self._hist.items()}

    def snapshot(self) -> dict:
        """Histograms + event counters + ring pressure, JSON-ready (the
        ``trace`` section of /api/v1/metrics)."""
        with self._lock:
            return {
                "enabled": self._active,
                "ring": {
                    "capacity": self._ring.maxlen,
                    "size": len(self._ring),
                    "appended": self._appended,
                    "evicted": self._appended - len(self._ring),
                },
                "histograms": {n: h.snapshot() for n, h in sorted(self._hist.items())},
                "events": dict(sorted(self._counters.items())),
            }

    def ring_records(self) -> list[dict]:
        """A consistent copy of the ring (tests; the exporter)."""
        with self._lock:
            return list(self._ring)

    # -- export ----------------------------------------------------------

    def _chrome_events(self) -> Iterator[dict]:
        with self._lock:
            ring = list(self._ring)
            names = dict(self._thread_names)
            epoch = self._epoch_ns
        pid = os.getpid()
        for tid, tname in names.items():
            yield {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        for r in ring:
            ev: dict[str, Any] = {
                "name": r["name"],
                "cat": r["name"].partition(".")[0],
                "ph": r["ph"],
                "ts": (r["t"] - epoch) / 1e3,  # µs
                "pid": pid,
                "tid": r["tid"],
                "args": r["args"],
            }
            if r["ph"] == "X":
                ev["dur"] = r["d"] / 1e3
            else:
                ev["s"] = "t"  # instant scoped to its thread
            yield ev

    def export_chrome(self, path: str | None = None) -> dict:
        """Render the ring as a Chrome trace-event document (the JSON
        object format, so Perfetto metadata can ride along); write it
        to ``path`` when given.  Returns the document either way.

        The ``otherData`` metadata carries what the RING cannot: the
        per-phase histogram totals (``phase_totals``) and the eviction
        count, so a consumer of an export whose ring wrapped knows
        exactly how many records were dropped and what the aggregate
        timings were anyway — the "no silent caps" rule
        (docs/observability.md); and ``epoch_unix_s``, the wall-clock
        instant of this plane's perf_counter epoch, which is what lets
        ``merge_chrome_traces`` align exports from different processes
        (each plane's ``ts`` values are relative to its own epoch) on
        one timeline."""
        now_wall = time.time()
        now_ns = time.perf_counter_ns()
        with self._lock:
            phase = {
                n: [round(h.total, 6), h.count]
                for n, h in sorted(self._hist.items())
            }
            appended = self._appended
            size = len(self._ring)
            epoch = self._epoch_ns
        doc = {
            "traceEvents": list(self._chrome_events()),
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "ksim_tpu.obs",
                "pid": os.getpid(),
                "epoch_unix_s": round(now_wall - (now_ns - epoch) / 1e9, 6),
                "phase_totals": phase,
                "ring": {
                    "appended": appended,
                    "size": size,
                    "evicted": appended - size,
                },
            },
        }
        if path:
            # Crash-atomic, same discipline as lease/journal compaction:
            # a reader (the fleet trace merge) never sees a torn file.
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return doc


# ---------------------------------------------------------------------------
# Stats providers (non-timing evidence merged into /api/v1/metrics)
# ---------------------------------------------------------------------------

_providers: dict[str, Callable[[], dict]] = {}  # guarded-by: _providers_lock
_providers_lock = threading.Lock()

#: Top-level sections of the merged /api/v1/metrics document that a
#: provider must not shadow (the endpoint merges providers at the top
#: level, so a collision would silently clobber a core section).
RESERVED_PROVIDER_NAMES = frozenset({"counters", "timings", "trace", "faults"})


def register_provider(name: str, fn: Callable[[], dict]) -> None:
    """Register (or replace) a named evidence provider.  The metrics
    endpoint snapshots every provider per GET — e.g. the CURRENT run's
    ``ReplayDriver.stats()`` registers under ``"replay"`` (latest
    driver wins; one driver exists per ScenarioRunner run)."""
    if name in RESERVED_PROVIDER_NAMES:
        raise ValueError(
            f"provider name {name!r} shadows a core /api/v1/metrics section"
        )
    with _providers_lock:
        _providers[name] = fn


def provider_snapshots() -> dict[str, dict]:
    """All providers' current snapshots; a provider that raises reports
    its error instead of poisoning the metrics document."""
    with _providers_lock:
        items = list(_providers.items())
    out: dict[str, dict] = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:  # evidence endpoint must never 500
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


#: The process-global plane every span/event site checks.  ``KSIM_TRACE*``
#: configures it at import so subprocess children (bench rungs, the make
#: trace child) inherit tracing through the environment — the stdlib-only
#: bench parent never has to import this module.
TRACE = TracePlane()
TRACE.configure_from_env()


# ---------------------------------------------------------------------------
# Fleet observability plane (docs/observability.md "Fleet observability")
#
# Each fleet member publishes its merged evidence document
# crash-atomically to KSIM_JOBS_DIR/obs/<worker_id>.json on a cadence
# (KSIM_OBS_PUBLISH_S; the obs-publisher thread in jobs/fleet.py) and
# once at clean shutdown; the front door folds every published snapshot
# into one fleet-scope document (counters sum, histograms merge
# bucket-wise exactly) and renders either scope as Prometheus text
# exposition.  Everything here is stdlib-only, like the rest of the
# module.
# ---------------------------------------------------------------------------

#: Subdirectory of KSIM_JOBS_DIR holding published worker snapshots.
#: Created lazily by the FIRST publish — with publishing off
#: (KSIM_OBS_PUBLISH_S=0) it never appears.
OBS_DIR = "obs"

_STARTED_AT = time.time()
_seq_lock = threading.Lock()
_publish_seq = 0  # guarded-by: _seq_lock


def next_publish_seq() -> int:
    """Monotonic per-process snapshot sequence number — lets a consumer
    of ``obs/<worker_id>.json`` distinguish "worker restarted" (seq
    reset) from "worker stalled" (seq frozen, published_at aging)."""
    global _publish_seq
    with _seq_lock:
        _publish_seq += 1
        return _publish_seq


def process_identity(
    *, role: "str | None" = None, worker_id: "str | None" = None
) -> dict:
    """The process-identity block every metrics document carries (solo
    ``/api/v1/metrics`` and published fleet snapshots alike): who
    produced this evidence, from which process, alive since when."""
    return {
        "role": role or "solo",
        "worker_id": worker_id or f"w{os.getpid()}",
        "pid": os.getpid(),
        "started_at": round(_STARTED_AT, 3),
        "uptime_s": round(time.time() - _STARTED_AT, 3),
    }


def _atomic_json(path: str, doc: dict) -> None:
    """tmp + fsync + os.replace — the journal-compaction discipline
    (jobs/fleet.py ``LeasePlane.maybe_compact``): a crashed writer
    leaves the previous snapshot intact, never a torn file."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def publish_snapshot(
    jobs_dir: str,
    doc: dict,
    *,
    worker_id: str,
    trace_doc: "dict | None" = None,
) -> str:
    """Write one worker's telemetry snapshot (and optionally its merged
    Chrome trace export) crash-atomically under ``<jobs_dir>/obs/``.
    Returns the snapshot path."""
    with TRACE.span("obs.publish", worker=worker_id):
        obs_dir = os.path.join(jobs_dir, OBS_DIR)
        os.makedirs(obs_dir, exist_ok=True)
        path = os.path.join(obs_dir, f"{worker_id}.json")
        _atomic_json(path, doc)
        if trace_doc is not None:
            _atomic_json(
                os.path.join(obs_dir, f"{worker_id}.trace.json"), trace_doc
            )
        return path


def _read_json_docs(obs_dir: str, suffix: str) -> "dict[str, dict]":
    out: dict[str, dict] = {}
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(suffix):
            continue
        if suffix == ".json" and name.endswith(".trace.json"):
            continue
        try:
            with open(
                os.path.join(obs_dir, name), "r", encoding="utf-8"
            ) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # mid-replace or torn: the previous read stands
        if isinstance(doc, dict):
            out[name[: -len(suffix)]] = doc
    return out


def read_fleet_snapshots(jobs_dir: str) -> "dict[str, dict]":
    """All published worker snapshots, by worker id.  Unreadable files
    are skipped (a concurrent os.replace can momentarily lose the race
    with listdir); staleness judgment belongs to ``merge_fleet_docs``,
    not here."""
    return _read_json_docs(os.path.join(jobs_dir, OBS_DIR), ".json")


def read_fleet_traces(jobs_dir: str) -> "dict[str, dict]":
    """All published worker Chrome-trace exports, by worker id."""
    return _read_json_docs(os.path.join(jobs_dir, OBS_DIR), ".trace.json")


def merge_fleet_docs(
    docs: "dict[str, dict]",
    *,
    now: "float | None" = None,
    stale_after: "float | None" = None,
) -> dict:
    """Fold per-worker snapshot documents into ONE fleet document:
    counters and event/fault counts SUM; latency histograms (Metrics
    timings and trace-plane span histograms alike) merge bucket-wise
    exactly into ``timings``; each worker's full document survives
    under ``workers[<id>]`` with its identity block plus ``stale_s`` /
    ``stale`` — a dead worker is FLAGGED (and an ``obs.snapshot_stale``
    event fires), never silently dropped.  A snapshot is stale past
    ``stale_after`` seconds (default: 3x its own published cadence,
    floored at 1 s)."""
    with TRACE.span("obs.fleet_merge", workers=len(docs)):
        if now is None:
            now = time.time()
        workers: dict[str, dict] = {}
        counters: dict[str, float] = {}
        events: dict[str, int] = {}
        faults: dict[str, dict] = {}
        hists: dict[str, LatencyHistogram] = {}
        for wid in sorted(docs):
            doc = docs[wid]
            ident = doc.get("process") or {}
            published = float(ident.get("published_at") or 0.0)
            cadence = float(ident.get("publish_s") or 0.0) or 10.0
            stale_s = max(0.0, now - published) if published else None
            limit = (
                stale_after
                if stale_after is not None
                else max(3.0 * cadence, 1.0)
            )
            stale = stale_s is None or stale_s > limit
            if stale:
                TRACE.event(
                    "obs.snapshot_stale",
                    worker=wid,
                    stale_s=None if stale_s is None else round(stale_s, 3),
                )
            wdoc = dict(doc)
            wdoc["stale"] = stale
            wdoc["stale_s"] = (
                None if stale_s is None else round(stale_s, 3)
            )
            workers[wid] = wdoc
            for name, v in (doc.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[name] = counters.get(name, 0) + v
            trace = doc.get("trace") or {}
            for name, v in (trace.get("events") or {}).items():
                if isinstance(v, (int, float)):
                    events[name] = events.get(name, 0) + int(v)
            for section in (
                doc.get("timings") or {},
                trace.get("histograms") or {},
            ):
                for name, snap in section.items():
                    if isinstance(snap, dict):
                        hists.setdefault(
                            name, LatencyHistogram()
                        ).merge_snapshot(snap)
            for site, c in (doc.get("faults") or {}).items():
                if not isinstance(c, dict):
                    continue
                agg = faults.setdefault(site, {"calls": 0, "fired": 0})
                agg["calls"] += int(c.get("calls") or 0)
                agg["fired"] += int(c.get("fired") or 0)
        return {
            "scope": "fleet",
            "generated_at": round(now, 3),
            "workers": workers,
            "counters": counters,
            "timings": {n: h.snapshot() for n, h in sorted(hists.items())},
            "trace": {"events": events},
            "faults": faults,
        }


def _flow_events(events: "list[dict]") -> "list[dict]":
    """Chrome flow events (``s``/``t``/``f`` phases) stitching each
    job's ``jobs.enqueue`` -> ``jobs.fleet_claim`` -> ``jobs.run``
    records into one arrow across process lanes.  Only COMPLETE triples
    emit — a partial chain (job still queued, ring evicted an anchor)
    draws no arrow rather than a misleading stub."""
    anchors: dict[str, dict] = {}
    want = {
        "jobs.enqueue": "s",
        "jobs.fleet_claim": "t",
        "jobs.run": "f",
    }
    for ev in events:
        ph = want.get(ev.get("name") or "")
        if ph is None:
            continue
        args = ev.get("args") or {}
        jid = args.get("job")
        if not isinstance(jid, str):
            continue
        anchors.setdefault(jid, {}).setdefault(ph, ev)
    out: list[dict] = []
    for idx, jid in enumerate(sorted(anchors)):
        chain = anchors[jid]
        if len(chain) != 3:
            continue
        for ph in ("s", "t", "f"):
            ev = chain[ph]
            rec = {
                "ph": ph,
                "name": "jobs.flow",
                "cat": "jobs",
                "id": idx + 1,
                "ts": ev.get("ts", 0),
                "pid": ev.get("pid"),
                "tid": ev.get("tid"),
                "args": {"job": jid},
            }
            if ph == "f":
                rec["bp"] = "e"  # bind the arrow end to the run slice
            out.append(rec)
    return out


def merge_chrome_traces(
    docs: "dict[str, dict]", *, flows: bool = False
) -> dict:
    """Merge per-process Chrome trace exports into ONE document with
    one process lane per worker.  Each export's ``ts`` values are
    relative to its own plane's perf_counter epoch; the exports'
    ``epoch_unix_s`` anchors rebase them all onto the EARLIEST epoch,
    so cross-process ordering is honest to wall-clock sync.  The
    merged document records its own base epoch, so merges compose
    (a worker's local global+per-job merge feeds the frontdoor's
    fleet merge).  ``flows=True`` additionally synthesizes the
    submit->claim->run flow arrows (``_flow_events``)."""
    with TRACE.span("obs.fleet_merge", traces=len(docs)):
        epochs: dict[str, float] = {}
        for wid, doc in docs.items():
            od = doc.get("otherData") or {}
            try:
                epochs[wid] = float(od.get("epoch_unix_s") or 0.0)
            except (TypeError, ValueError):
                epochs[wid] = 0.0
        known = [e for e in epochs.values() if e]
        base = min(known) if known else 0.0
        merged: list[dict] = []
        lane_names: dict = {}  # pid -> worker id (first wins)
        named: set = set()  # pids already carrying process_name metadata
        for wid in sorted(docs):
            doc = docs[wid]
            od = doc.get("otherData") or {}
            doc_pid = od.get("pid")
            off_us = (epochs[wid] - base) * 1e6 if epochs[wid] else 0.0
            for ev in doc.get("traceEvents") or ():
                ev = dict(ev)
                pid = ev.get("pid", doc_pid)
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    named.add(pid)
                elif pid is not None and pid not in lane_names:
                    lane_names[pid] = wid
                if "ts" in ev and off_us:
                    ev["ts"] = ev["ts"] + off_us
                merged.append(ev)
        meta = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": wid},
            }
            for pid, wid in lane_names.items()
            if pid not in named
        ]
        events = meta + merged
        if flows:
            events = events + _flow_events(merged)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "ksim_tpu.obs",
                "pid": os.getpid(),
                "merged": sorted(docs),
                "epoch_unix_s": base,
            },
        }


# -- Prometheus text exposition ---------------------------------------------


def _escape_label(value) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_value(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return format(f, ".10g")


def _fmt_edge(edge: float) -> str:
    return format(edge, ".9g")


def _sample_line(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in labels.items()
        )
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def _emit_histogram(
    out: "list[tuple[str, dict, Any]]", family: str, labels: dict, snap: dict
) -> None:
    """Expand one LatencyHistogram snapshot into cumulative ``_bucket``
    samples over EVERY fixed edge (plus ``+Inf``), ``_sum`` and
    ``_count`` — the native Prometheus histogram shape, ``le``
    semantics matching ``observe``'s bisect_left exactly."""
    counts = [0] * (len(LatencyHistogram.EDGES) + 1)
    for edge, c in snap.get("buckets") or ():
        i = len(LatencyHistogram.EDGES) if edge is None else _EDGE_INDEX[edge]
        counts[i] += int(c)
    cum = 0
    for i, edge in enumerate(LatencyHistogram.EDGES):
        cum += counts[i]
        out.append(
            (f"{family}_bucket", {**labels, "le": _fmt_edge(edge)}, cum)
        )
    cum += counts[-1]
    out.append((f"{family}_bucket", {**labels, "le": "+Inf"}, cum))
    out.append((f"{family}_sum", labels, snap.get("total_seconds") or 0.0))
    out.append((f"{family}_count", labels, snap.get("count") or 0))


def _expose_section(
    samples: "dict[str, list]", doc: dict, labels: dict
) -> None:
    """Render one solo-shaped metrics document (a worker snapshot or
    the serving process's own document) into per-family samples."""
    for name, v in sorted((doc.get("counters") or {}).items()):
        if isinstance(v, (int, float)):
            samples["ksim_counter_total"].append(
                ("ksim_counter_total", {**labels, "name": name}, v)
            )
    trace = doc.get("trace") or {}
    for name, v in sorted((trace.get("events") or {}).items()):
        if isinstance(v, (int, float)):
            samples["ksim_event_total"].append(
                ("ksim_event_total", {**labels, "name": name}, v)
            )
    ring = trace.get("ring") or {}
    if ring:
        samples["ksim_trace_ring_evicted_total"].append(
            (
                "ksim_trace_ring_evicted_total",
                labels,
                ring.get("evicted") or 0,
            )
        )
    merged_hists = dict(doc.get("timings") or {})
    merged_hists.update(trace.get("histograms") or {})
    for name in sorted(merged_hists):
        snap = merged_hists[name]
        if isinstance(snap, dict):
            _emit_histogram(
                samples["ksim_latency_seconds"],
                "ksim_latency_seconds",
                {**labels, "site": name},
                snap,
            )
    for site, c in sorted((doc.get("faults") or {}).items()):
        if not isinstance(c, dict):
            continue
        samples["ksim_fault_calls_total"].append(
            (
                "ksim_fault_calls_total",
                {**labels, "site": site},
                c.get("calls") or 0,
            )
        )
        samples["ksim_fault_fired_total"].append(
            (
                "ksim_fault_fired_total",
                {**labels, "site": site},
                c.get("fired") or 0,
            )
        )
    jobs = doc.get("jobs") or {}
    q = jobs.get("queue") or {}
    if q:
        samples["ksim_queue_depth"].append(
            ("ksim_queue_depth", labels, q.get("depth") or 0)
        )
        samples["ksim_queue_capacity"].append(
            ("ksim_queue_capacity", labels, q.get("capacity") or 0)
        )
    w = jobs.get("workers") or {}
    if w:
        samples["ksim_workers_pool"].append(
            ("ksim_workers_pool", labels, w.get("pool") or 0)
        )
        samples["ksim_workers_active"].append(
            ("ksim_workers_active", labels, w.get("active") or 0)
        )
    replay = doc.get("replay") or {}
    if isinstance(replay, dict) and "breaker_tripped" in replay:
        samples["ksim_breaker_open"].append(
            (
                "ksim_breaker_open",
                labels,
                1.0 if replay["breaker_tripped"] else 0.0,
            )
        )
    ident = doc.get("process") or {}
    if "uptime_s" in ident:
        samples["ksim_uptime_seconds"].append(
            ("ksim_uptime_seconds", labels, ident["uptime_s"])
        )


def render_prometheus(doc: dict) -> str:
    """Render a metrics document — solo (``/api/v1/metrics`` shape) or
    fleet (``merge_fleet_docs`` shape) — as Prometheus text exposition.
    Fleet scope renders PER-WORKER series only (``worker``/``role``
    labels); a scraper's ``sum()`` re-derives the fleet totals, so
    nothing is double-counted.  ``parse_prometheus`` round-trips and
    validates this output in-suite."""
    samples: dict[str, list] = {f["name"]: [] for f in _EXPO_FAMILIES}
    if doc.get("scope") == "fleet":
        for wid, wdoc in sorted((doc.get("workers") or {}).items()):
            ident = wdoc.get("process") or {}
            labels = {
                "worker": str(ident.get("worker_id") or wid),
                "role": str(ident.get("role") or ""),
            }
            _expose_section(samples, wdoc, labels)
            stale_s = wdoc.get("stale_s")
            if stale_s is not None:
                samples["ksim_snapshot_age_seconds"].append(
                    ("ksim_snapshot_age_seconds", labels, stale_s)
                )
            samples["ksim_up"].append(
                ("ksim_up", labels, 0.0 if wdoc.get("stale") else 1.0)
            )
    else:
        ident = doc.get("process") or {}
        labels = {
            "worker": str(ident.get("worker_id") or f"w{os.getpid()}"),
            "role": str(ident.get("role") or "solo"),
        }
        _expose_section(samples, doc, labels)
        samples["ksim_up"].append(("ksim_up", labels, 1.0))
    lines: list[str] = []
    for fam in _EXPO_FAMILIES:
        rows = samples[fam["name"]]
        if not rows:
            continue
        lines.append(f"# HELP {fam['name']} {fam['help']}")
        lines.append(f"# TYPE {fam['name']} {fam['kind']}")
        for name, labels, value in rows:
            lines.append(_sample_line(name, labels, value))
    return "\n".join(lines) + "\n"


_NAME_START = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_:"
)
_NAME_CHARS = _NAME_START | frozenset("0123456789")


def _parse_sample(line: str) -> "tuple[str, dict, float]":
    """Strict parse of one exposition sample line."""
    i = 0
    n = len(line)
    if not line or line[0] not in _NAME_START:
        raise ValueError(f"bad metric name: {line!r}")
    while i < n and line[i] in _NAME_CHARS:
        i += 1
    name = line[:i]
    labels: dict[str, str] = {}
    if i < n and line[i] == "{":
        i += 1
        while True:
            if i >= n:
                raise ValueError(f"unterminated label set: {line!r}")
            if line[i] == "}":
                i += 1
                break
            j = i
            while j < n and line[j] in _NAME_CHARS:
                j += 1
            key = line[i:j]
            if (
                not key
                or j + 1 >= n
                or line[j] != "="
                or line[j + 1] != '"'
            ):
                raise ValueError(f"bad label at col {i}: {line!r}")
            j += 2
            buf: list[str] = []
            while j < n and line[j] != '"':
                if line[j] == "\\":
                    if j + 1 >= n:
                        raise ValueError(f"bad escape: {line!r}")
                    esc = line[j + 1]
                    buf.append(
                        {"n": "\n", "\\": "\\", '"': '"'}.get(esc, esc)
                    )
                    j += 2
                else:
                    buf.append(line[j])
                    j += 1
            if j >= n:
                raise ValueError(f"unterminated label value: {line!r}")
            labels[key] = "".join(buf)
            j += 1
            if j < n and line[j] == ",":
                j += 1
            i = j
    rest = line[i:].strip()
    if not rest:
        raise ValueError(f"sample has no value: {line!r}")
    value_str = rest.split()[0]
    if value_str == "+Inf":
        value = float("inf")
    elif value_str == "-Inf":
        value = float("-inf")
    else:
        value = float(value_str)
    return name, labels, value


def parse_prometheus(text: str) -> "dict[str, dict]":
    """Stdlib validator for the exposition format: every sample must
    follow a ``# TYPE`` for its family, histogram samples must carry
    coherent ``le`` labels (cumulative, non-decreasing, ``+Inf``
    present and equal to ``_count``).  Returns families with their
    parsed samples; raises ``ValueError`` on any violation — the
    golden test pins the format by parser, not by hope."""
    families: dict[str, dict] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            families.setdefault(
                name, {"kind": None, "help": None, "samples": []}
            )["help"] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            fam = families.setdefault(
                name, {"kind": None, "help": None, "samples": []}
            )
            if fam["samples"]:
                raise ValueError(
                    f"line {lineno}: TYPE for {name!r} after its samples"
                )
            fam["kind"] = kind.strip()
            continue
        if line.startswith("#"):
            continue  # free comment
        try:
            name, labels, value = _parse_sample(line)
        except ValueError as e:
            raise ValueError(f"line {lineno}: {e}") from None
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                cand = name[: -len(suffix)]
                if families.get(cand, {}).get("kind") == "histogram":
                    base = cand
                    break
        fam = families.get(base)
        if fam is None or not fam["kind"]:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no preceding # TYPE"
            )
        if (
            fam["kind"] == "histogram"
            and name.endswith("_bucket")
            and "le" not in labels
        ):
            raise ValueError(
                f"line {lineno}: histogram bucket without le label"
            )
        fam["samples"].append({"name": name, "labels": labels, "value": value})
    for fname, fam in families.items():
        if fam["kind"] != "histogram":
            continue
        series: dict[tuple, dict] = {}
        for sample in fam["samples"]:
            name, labels, value = (
                sample["name"], sample["labels"], sample["value"]
            )
            key = tuple(
                sorted((k, v) for k, v in labels.items() if k != "le")
            )
            ent = series.setdefault(key, {"buckets": [], "count": None})
            if name.endswith("_bucket"):
                le = labels["le"]
                ent["buckets"].append(
                    (float("inf") if le == "+Inf" else float(le), value)
                )
            elif name.endswith("_count"):
                ent["count"] = value
        for key, ent in series.items():
            buckets = sorted(ent["buckets"])
            if not buckets or buckets[-1][0] != float("inf"):
                raise ValueError(
                    f"{fname}{dict(key)}: histogram missing +Inf bucket"
                )
            prev = -1.0
            for le, v in buckets:
                if v < prev:
                    raise ValueError(
                        f"{fname}{dict(key)}: bucket counts decrease at "
                        f"le={le}"
                    )
                prev = v
            if ent["count"] is not None and buckets[-1][1] != ent["count"]:
                raise ValueError(
                    f"{fname}{dict(key)}: +Inf bucket != _count"
                )
    return families


@atexit.register
def _export_at_exit() -> None:
    if TRACE.out_path and TRACE.active:
        try:
            TRACE.export_chrome(TRACE.out_path)
        except OSError:
            pass
