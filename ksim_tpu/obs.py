"""Process-global trace plane: spans, latency histograms, event ring.

The reference simulator's observability is the upstream scheduler's
Prometheus metrics plus klog (SURVEY §5); before this module the repo's
analogue was a mean-only ``Metrics`` counter/timer and scattered ad-hoc
dicts (``ReplayDriver.stats()``, ``FaultPlane`` site counters).  None of
it could answer the ROADMAP's open TPU wall-clock question — *where*
does the 50k trajectory spend its time, and *when* did a degradation
(fallback, watchdog timeout, breaker trip) actually happen.

This module is the single answer surface:

- **Spans** — named intervals on a monotonic clock (``TRACE.span``),
  one per pipeline phase (segment lower / dispatch / reconcile, the
  per-pass host step, write-back pushes, kubeapi requests).  Every span
  lands its duration in a fixed-bucket log-spaced latency histogram and
  (ring mode) a structured record in the event ring.
- **Events** — instants (``TRACE.event``): fallback reasons with the
  segment context, pass outcomes, fault-plane fires, breaker state
  changes, store-transaction commit/rollback.
- **Export** — the ring renders as Chrome trace-event JSON
  (``chrome://tracing`` / https://ui.perfetto.dev): spans become ``X``
  complete events nested per thread, instants become ``i`` events.
  ``KSIM_TRACE_OUT=path`` arms an atexit export, so any entrypoint can
  be traced from the environment alone; ``/api/v1/trace`` serves the
  same document live.

Observability is zero-perturbation by construction: nothing here reads
or writes scheduling state, so the churn behavior locks (repo
CLAUDE.md) hold byte-identically with tracing fully enabled —
tests/test_behavior_locks.py pins that.  With the plane fully disabled
every site costs ONE attribute check (``TRACE._active``) and nothing
else; the module is stdlib-only and never imports jax at module scope
(the optional ``jax.profiler.TraceAnnotation`` bridge is lazy and
guarded, so host spans can be correlated with device timelines when a
jax profile is being captured: ``KSIM_TRACE_JAX=1``).

Environment:

- ``KSIM_TRACE_OUT=path``  enable timing + ring; export Chrome trace
  JSON to ``path`` at process exit (and on demand).
- ``KSIM_TRACE=1``         enable timing + ring without a file.
- ``KSIM_TRACE=timing``    histograms/counters only (no ring storage).
- ``KSIM_TRACE_RING=N``    ring capacity (default 65536 records).
- ``KSIM_TRACE_JAX=1``     also wrap spans in
  ``jax.profiler.TraceAnnotation`` (guarded; no-op if jax is absent or
  no profiler session is active).

The span/event name taxonomy lives in ``SPAN_NAMES`` / ``EVENT_NAMES``
below; tests/test_obs.py's registry-sync test asserts every
``faults.py`` injection site and every replay fallback reason stays
covered (see docs/observability.md for the full table).
"""

from __future__ import annotations

import atexit
import bisect
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator

__all__ = [
    "TRACE",
    "TracePlane",
    "LatencyHistogram",
    "SPAN_NAMES",
    "EVENT_NAMES",
    "register_provider",
    "provider_snapshots",
]

# ---------------------------------------------------------------------------
# Taxonomy (docs/observability.md keeps the prose table in sync)
# ---------------------------------------------------------------------------

#: Interval (span) names.  The fault-plane injection sites
#: (faults.SITES) each fire INSIDE the span of the same name, so a
#: fault event always has an enclosing phase on the timeline.
SPAN_NAMES: tuple[str, ...] = (
    "replay.lower",  # segment lowering (engine/replay.py)
    "replay.prelower",  # NEXT window's speculative store-independent
    #                     prefix, overlapped with the in-flight dispatch
    #                     (runs on the main thread INSIDE the dispatch
    #                     span's wall-clock window — the two are
    #                     concurrent by design, not additive)
    "replay.dispatch",  # device dispatch incl. watchdog wait
    "replay.reconcile",  # staged store reconcile (the segment txn)
    "runner.step",  # one per-pass host step (ops + flush + schedule)
    "service.schedule",  # one scheduling pass (scheduler/service.py)
    "writeback.push",  # live-cluster write-back push
    "kubeapi.request",  # any kube-apiserver HTTP request
    "jobs.run",  # one tenant job end-to-end on a job-plane worker
    #              (ksim_tpu/jobs/manager.py; recorded on the JOB's
    #              private plane via the worker's scoped override)
    "scenario.ingest",  # one trace ingestion: parse + resample +
    #                     compile of a real cluster trace into the
    #                     operation stream (ksim_tpu/traces/compile.py;
    #                     args carry format/records/ops)
    "jobs.journal_append",  # one durable append to the job journal
    #                         (ksim_tpu/jobs/journal.py; the write-ahead
    #                         record behind every submission/transition)
    "jobs.journal_replay",  # one startup journal replay: scan + torn-
    #                         tail truncation + registry reconstruction
    "jobs.checkpoint_append",  # one segment-checkpoint record built and
    #                            durably appended to the job journal
    #                            (ksim_tpu/jobs/manager.py; wraps the
    #                            nested jobs.journal_append span)
    "jobs.checkpoint_restore",  # one restore attempt from a journaled
    #                             checkpoint: store + service carries
    #                             reconstructed on the worker thread
    #                             before the suffix replay
    "jobs.lease_claim",  # one fleet claim attempt: fold the lease file
    #                      under the exclusive flock, decide, append
    #                      (ksim_tpu/jobs/fleet.py; refusals return
    #                      inside the span without a claim record)
    "jobs.lease_renew",  # one heartbeat batch renewing this worker's
    #                      live leases (args.n — a missed batch is
    #                      survivable until lease expiry)
)

#: Instant event names.
EVENT_NAMES: tuple[str, ...] = (
    "replay.fallback",  # segment rejected/degraded; args.reason is the
    #                     stable histogram reason (ReplayDriver._reject)
    "replay.watchdog_timeout",  # a dispatch exceeded the watchdog
    "replay.breaker_open",  # the circuit breaker tripped (args.cause:
    #                         device_error / reconcile_fault /
    #                         probe_failed — the last is a half-open
    #                         probe that failed and re-opened with a
    #                         doubled cooldown)
    "service.pass",  # pass outcome: attempts/scheduled/unschedulable
    "fault.fired",  # the fault plane injected at args.site
    "store.txn_commit",  # segment transaction committed (args.writes)
    "store.txn_rollback",  # segment transaction rolled back
    "replay.cache_invalidate",  # the lowered-universe cache flushed
    #                             (args.reason: fallback / rollback /
    #                             epoch_mismatch / epoch_raced /
    #                             sched_config / no_plan)
    "replay.fleet_lane_fallback",  # one fleet lane left the convergent
    #                                cohort (args.lane, args.reason) and
    #                                continues on the solo device path
    #                                (engine/fleet.py)
    "jobs.enqueue",  # a tenant job entered the job queue (args.job,
    #                  args.priority — ksim_tpu/jobs/manager.py)
    "job.cancelled",  # a tenant job was cancelled (queued or mid-run;
    #                   mid-segment cancellation rolls the in-flight
    #                   segment transaction back first)
    "replay.breaker_probe",  # the open breaker's cooldown elapsed and
    #                          ONE probe segment was admitted to the
    #                          device path (half-open state)
    "replay.breaker_close",  # a probe dispatch came back healthy: the
    #                          breaker closed and the driver re-promoted
    #                          to the device path
    "compilecache.evict",  # an on-disk serialized executable was
    #                        discarded (args.reason: corrupt /
    #                        key_mismatch / deserialize_failed /
    #                        exec_failed — engine/compilecache.py)
    "jobs.journal_recover",  # startup journal replay reconstructed the
    #                          job registry (args: jobs / interrupted /
    #                          resumed / truncated_bytes)
    "jobs.checkpoint",  # segment-checkpoint cadence outcome: written
    #                     (args: job / segment / cursor / bytes) or
    #                     skipped (args.skipped=True, args.reason:
    #                     max_bytes / waiting_pods / append_failed —
    #                     a skip never fails the job)
    "jobs.checkpoint_restore",  # restore-from-checkpoint outcome
    #                             (args.restored True/False; a failed
    #                             attempt falls back to the previous
    #                             checkpoint, then to scratch)
    "jobs.fleet_claim",  # a fleet member won a job lease (args: job /
    #                      worker / epoch / takeover — takeover=True is
    #                      the fail-over path re-claiming an expired
    #                      lease; ksim_tpu/jobs/fleet.py)
    "jobs.lease_expired",  # a lease aged out un-renewed and a survivor
    #                        took the job over (args: job / worker — the
    #                        DEAD owner being charged — / epoch)
)

_KNOWN_NAMES = frozenset(SPAN_NAMES) | frozenset(EVENT_NAMES)


# ---------------------------------------------------------------------------
# Latency histogram
# ---------------------------------------------------------------------------


def _log_edges() -> tuple[float, ...]:
    """Fixed log-spaced bucket upper edges: 4 per decade from 1 µs to
    100 s (33 edges; an overflow bucket catches the rest).  Fixed — not
    adaptive — so two snapshots (or two processes) always merge and
    compare bucket-for-bucket."""
    return tuple(1e-6 * 10 ** (i / 4) for i in range(33))


class LatencyHistogram:
    """Fixed-bucket latency histogram (seconds).  NOT thread-safe on its
    own — callers (``TracePlane``, ``util.Metrics``) hold their lock."""

    EDGES: tuple[float, ...] = _log_edges()

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self) -> None:
        self.counts = [0] * (len(self.EDGES) + 1)  # +1 = overflow
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = 0.0

    def observe(self, seconds: float) -> None:
        # bisect_left: an observation exactly ON an edge belongs to the
        # bucket whose upper edge it is (le semantics, like Prometheus).
        self.counts[bisect.bisect_left(self.EDGES, seconds)] += 1
        self.count += 1
        self.total += seconds
        if seconds < self.vmin:
            self.vmin = seconds
        if seconds > self.vmax:
            self.vmax = seconds

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (upper edge of the
        bucket holding the q-th observation; the overflow bucket
        reports the observed max)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                # Clamped: a bucket's upper edge can exceed anything
                # actually observed.
                return (
                    min(self.EDGES[i], self.vmax)
                    if i < len(self.EDGES)
                    else self.vmax
                )
        return self.vmax

    def snapshot(self) -> dict:
        """JSON-ready view.  Keeps the legacy mean-only timer keys
        (``total_seconds`` / ``count`` / ``mean_seconds`` — pinned by
        tests/test_server.py) and adds the histogram: nonzero buckets
        as ``[upper_edge_seconds, count]`` pairs plus estimated
        quantiles."""
        if not self.count:
            return {"count": 0, "total_seconds": 0.0, "mean_seconds": 0.0}
        buckets = [
            [round(self.EDGES[i], 9) if i < len(self.EDGES) else None, c]
            for i, c in enumerate(self.counts)
            if c
        ]
        return {
            "count": self.count,
            "total_seconds": round(self.total, 6),
            "mean_seconds": round(self.total / self.count, 6),
            "min_seconds": round(self.vmin, 6),
            "max_seconds": round(self.vmax, 6),
            "p50_seconds": round(self.quantile(0.50), 6),
            "p90_seconds": round(self.quantile(0.90), 6),
            "p99_seconds": round(self.quantile(0.99), 6),
            "buckets": buckets,
        }


# ---------------------------------------------------------------------------
# The plane
# ---------------------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing context manager — the whole disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    """One live span.  Records at EXIT: a span that never exits (a
    wedged dispatch abandoned with its watchdog worker) simply leaves
    no record — the caller-side watchdog timeout event is the evidence
    for that case."""

    __slots__ = ("_plane", "name", "args", "_t0", "_jax_ctx")

    def __init__(self, plane: "TracePlane", name: str, args: dict) -> None:
        self._plane = plane
        self.name = name
        self.args = args
        self._t0 = 0
        self._jax_ctx = None

    def __enter__(self):
        plane = self._plane
        tl = plane._tls
        tl.depth = getattr(tl, "depth", 0) + 1
        if plane._jax_bridge:
            # Guarded device-timeline bridge: annotations show up in a
            # captured jax profile next to the XLA ops they enclose.
            try:
                import jax

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        self._t0 = time.perf_counter_ns()
        return self

    def set(self, **args) -> None:
        """Refine span attributes mid-flight (recorded at exit) — for
        values the caller only learns inside the span, e.g. the ACTUAL
        lowered step count of a window that hit a vocabulary miss."""
        self.args.update(args)

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        plane = self._plane
        tl = plane._tls
        depth = getattr(tl, "depth", 1)
        tl.depth = depth - 1
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        plane._record_span(self.name, self._t0, t1, depth - 1, self.args)
        return False


class _PlaneScope:
    """Context manager installing an override plane for the current
    thread (``TracePlane.scoped``); restores the previous override on
    exit, so scopes nest."""

    __slots__ = ("_plane", "_override", "_prev")

    def __init__(self, plane: "TracePlane", override: "TracePlane | None") -> None:
        self._plane = plane
        self._override = override
        self._prev = None

    def __enter__(self):
        tls = self._plane._tls
        self._prev = getattr(tls, "scope", None)
        tls.scope = self._override
        return self._override

    def __exit__(self, *exc):
        self._plane._tls.scope = self._prev
        return False


class TracePlane:
    """Bounded, thread-safe trace storage — instance-scoped since
    round 13 (the job plane), with the process-global ``TRACE`` as the
    default instance.

    Three independently useful layers, one ``_active`` gate:

    - per-name latency histograms + event counters (``timing``),
    - the structured event ring (``ring``),
    - the Chrome-trace exporter over the ring.

    Thread-safe: spans/events land from the scheduler watch loop, the
    write-back thread, HTTP handler threads, and the replay dispatch
    worker concurrently; one leaf lock guards all storage (nothing
    under it calls out, so it cannot participate in a lock cycle).

    **Scoped override** (multi-tenancy): ``TRACE.scoped(plane)``
    installs ``plane`` as the CURRENT THREAD's recording target — every
    ``span``/``event``/``ensure_timing``/``phase_totals`` call on the
    default plane delegates to it until the scope exits.  Call sites
    keep addressing the module-global ``TRACE``; a tenant-job worker
    (ksim_tpu/jobs) wraps its run in a scope and gets a private ring,
    private histograms, and per-record ``tags`` (e.g. ``job=<id>``)
    without a single call-site change.  The replay executor propagates
    the scope onto its watchdogged dispatch worker
    (engine/replay.py ``_run_watchdogged``), so spans/events emitted
    there stay attributed to the owning job.  Reads of a SPECIFIC
    plane's storage (``snapshot``/``ring_records``/``export_chrome``)
    never delegate — an HTTP handler asking the global plane gets the
    global plane.

    ``tags`` merge into every recorded span/event's args (the job id on
    every record); ``sink`` — set via ``set_sink`` — receives each
    record dict AFTER the storage lock is released (it may fan records
    into an SSE stream; a raising sink is swallowed)."""

    def __init__(self, *, tags: "dict | None" = None) -> None:
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._active = False
        # Set by an explicit disable() / KSIM_TRACE=off: ensure_timing's
        # convenience activation must never override an operator's
        # stated choice.
        self._user_disabled = False
        self._ring_on = False  # guarded-by: _lock
        self._jax_bridge = False
        self.out_path: str | None = None
        # Constant after construction (read-only on the hot path, so no
        # lock): args merged into every record, and the out-of-lock
        # record callback.
        self._tags: dict = dict(tags or {})
        self._sink: "Callable[[dict], None] | None" = None
        self._epoch_ns = time.perf_counter_ns()  # guarded-by: _lock
        self._hist: dict[str, LatencyHistogram] = {}  # guarded-by: _lock
        self._counters: dict[str, int] = {}  # guarded-by: _lock
        self._ring: deque = deque(maxlen=65536)  # guarded-by: _lock
        # guarded-by: _lock (ring pressure evidence: dropped = appended - len)
        self._appended = 0
        self._thread_names: dict[int, str] = {}  # guarded-by: _lock

    # -- configuration ---------------------------------------------------

    def enable(self, *, ring: bool = True, out: str | None = None) -> None:
        """Turn the plane on.  ``ring=False`` keeps histograms/counters
        only (no per-record storage); ``out`` arms the atexit Chrome
        export (also settable via ``KSIM_TRACE_OUT``)."""
        with self._lock:
            self._ring_on = ring or out is not None
            if out is not None:
                self.out_path = out
            self._user_disabled = False
            self._active = True

    def disable(self) -> None:
        """One attribute check per site from here on (storage kept;
        ``reset`` clears it).  Sticky against ``ensure_timing``: only an
        explicit ``enable`` turns the plane back on."""
        self._active = False
        self._user_disabled = True

    def reset(self) -> None:
        """Drop all recorded state (test teardown); enablement flags
        and the ring capacity survive."""
        with self._lock:
            self._hist.clear()
            self._counters.clear()
            self._ring.clear()
            self._appended = 0
            self._thread_names.clear()
            self._epoch_ns = time.perf_counter_ns()

    def configure_from_env(self, environ=os.environ) -> None:
        """Apply ``KSIM_TRACE*`` (import-time; tests re-invoke)."""
        cap = environ.get("KSIM_TRACE_RING", "")
        if cap:
            try:
                maxlen = max(int(cap), 16)
            except ValueError:
                maxlen = None
            if maxlen is not None:
                # Swap under the lock: a concurrent event() append must
                # never land in an orphaned deque (that record would
                # vanish and the eviction accounting would over-report).
                with self._lock:
                    self._ring = deque(self._ring, maxlen=maxlen)
        self._jax_bridge = environ.get("KSIM_TRACE_JAX", "") == "1"
        out = environ.get("KSIM_TRACE_OUT", "")
        mode = environ.get("KSIM_TRACE", "")
        if mode in ("0", "off"):
            # The operator's opt-out beats everything, including a
            # KSIM_TRACE_OUT a wrapper script may have exported — the
            # same never-override-a-stated-choice contract as
            # ensure_timing vs disable().
            self.disable()
        elif out:
            self.enable(ring=True, out=out)
        elif mode:
            self.enable(ring=(mode != "timing"))

    @property
    def active(self) -> bool:
        return self._active

    def set_sink(self, sink: "Callable[[dict], None] | None") -> None:
        """Install (or clear) the record callback.  Set before the plane
        starts receiving records — the hot path reads it unlocked."""
        self._sink = sink

    # -- scoped override -------------------------------------------------

    def scoped(self, plane: "TracePlane | None") -> _PlaneScope:
        """Install ``plane`` as the current thread's recording target
        for ``span``/``event``/``ensure_timing``/``phase_totals`` calls
        on THIS plane (``None`` = a no-op scope).  Used by the job plane
        to give each tenant job a private trace plane without changing
        any call site; the previous scope restores on exit."""
        return _PlaneScope(self, plane)

    def scope(self) -> "TracePlane | None":
        """The current thread's override plane, if any — captured by the
        replay executor before handing work to its dispatch worker so
        the scope survives the thread hop."""
        return getattr(self._tls, "scope", None)

    def scope_tags(self) -> dict:
        """The effective record tags for the calling thread (the
        override plane's, else this plane's) — e.g. the owning job id
        for the compile cache's per-tenant sharing evidence."""
        ov = getattr(self._tls, "scope", None)
        return (ov if ov is not None else self)._tags

    def ensure_timing(self) -> None:
        """Idempotent timing-only activation.  ScenarioRunner calls this
        so per-phase wall-clock totals always exist (the histogram cost
        is two clock reads + one locked increment per span, at
        segment/pass granularity); ring storage stays off unless the
        operator armed it, and an explicit ``disable()`` /
        ``KSIM_TRACE=off`` wins — convenience activation never
        overrides a stated opt-out."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            ov.ensure_timing()
            return
        if not self._active and not self._user_disabled:
            self.enable(ring=False)

    # -- the hot path ----------------------------------------------------

    def span(self, name: str, **args):
        """Open a named span; a no-op singleton when the plane is off
        (the disabled path is one TLS read + one attribute check).  A
        thread-scoped override plane (``scoped``) takes the record
        instead."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            return ov.span(name, **args)
        if not self._active:
            return _NOOP
        return _Span(self, name, args)

    def event(self, name: str, **args) -> None:
        """Record one instant event (counted always; stored when the
        ring is on)."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            ov.event(name, **args)
            return
        if not self._active:
            return
        now = time.perf_counter_ns()
        tid = threading.get_ident()
        if self._tags:
            args = {**self._tags, **args}
        sink = self._sink
        rec = None
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + 1
            if self._ring_on or sink is not None:
                rec = {"ph": "i", "name": name, "t": now, "tid": tid, "args": args}
                if self._ring_on:
                    self._note_thread(tid)
                    self._appended += 1
                    self._ring.append(rec)
        if rec is not None and sink is not None:
            try:
                sink(rec)
            except Exception:  # a broken sink must not break the plane
                pass

    def _record_span(
        self, name: str, t0: int, t1: int, depth: int, args: dict
    ) -> None:
        tid = threading.get_ident()
        if self._tags:
            args = {**self._tags, **args}
        sink = self._sink
        rec = None
        with self._lock:
            hist = self._hist.get(name)
            if hist is None:
                hist = self._hist[name] = LatencyHistogram()
            hist.observe((t1 - t0) / 1e9)
            if self._ring_on or sink is not None:
                rec = {
                    "ph": "X",
                    "name": name,
                    "t": t0,
                    "d": t1 - t0,
                    "tid": tid,
                    "depth": depth,
                    "args": args,
                }
                if self._ring_on:
                    self._note_thread(tid)
                    self._appended += 1
                    self._ring.append(rec)
        if rec is not None and sink is not None:
            try:
                sink(rec)
            except Exception:  # a broken sink must not break the plane
                pass

    def _note_thread(self, tid: int) -> None:  # ksimlint: lock-held(_lock)
        if tid not in self._thread_names:
            t = threading.current_thread()
            self._thread_names[tid] = t.name

    # -- evidence --------------------------------------------------------

    def phase_totals(self) -> dict[str, tuple[float, int]]:
        """Per-span-name ``(total_seconds, count)`` — the runner diffs
        two of these around a run for its per-phase breakdown.  Follows
        the thread's scoped override, so a job-scoped run's phase split
        reads the JOB's histograms."""
        ov = getattr(self._tls, "scope", None)
        if ov is not None:
            return ov.phase_totals()
        with self._lock:
            return {n: (h.total, h.count) for n, h in self._hist.items()}

    def snapshot(self) -> dict:
        """Histograms + event counters + ring pressure, JSON-ready (the
        ``trace`` section of /api/v1/metrics)."""
        with self._lock:
            return {
                "enabled": self._active,
                "ring": {
                    "capacity": self._ring.maxlen,
                    "size": len(self._ring),
                    "appended": self._appended,
                    "evicted": self._appended - len(self._ring),
                },
                "histograms": {n: h.snapshot() for n, h in sorted(self._hist.items())},
                "events": dict(sorted(self._counters.items())),
            }

    def ring_records(self) -> list[dict]:
        """A consistent copy of the ring (tests; the exporter)."""
        with self._lock:
            return list(self._ring)

    # -- export ----------------------------------------------------------

    def _chrome_events(self) -> Iterator[dict]:
        with self._lock:
            ring = list(self._ring)
            names = dict(self._thread_names)
            epoch = self._epoch_ns
        pid = os.getpid()
        for tid, tname in names.items():
            yield {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        for r in ring:
            ev: dict[str, Any] = {
                "name": r["name"],
                "cat": r["name"].partition(".")[0],
                "ph": r["ph"],
                "ts": (r["t"] - epoch) / 1e3,  # µs
                "pid": pid,
                "tid": r["tid"],
                "args": r["args"],
            }
            if r["ph"] == "X":
                ev["dur"] = r["d"] / 1e3
            else:
                ev["s"] = "t"  # instant scoped to its thread
            yield ev

    def export_chrome(self, path: str | None = None) -> dict:
        """Render the ring as a Chrome trace-event document (the JSON
        object format, so Perfetto metadata can ride along); write it
        to ``path`` when given.  Returns the document either way."""
        doc = {
            "traceEvents": list(self._chrome_events()),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "ksim_tpu.obs", "pid": os.getpid()},
        }
        if path:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        return doc


# ---------------------------------------------------------------------------
# Stats providers (non-timing evidence merged into /api/v1/metrics)
# ---------------------------------------------------------------------------

_providers: dict[str, Callable[[], dict]] = {}  # guarded-by: _providers_lock
_providers_lock = threading.Lock()

#: Top-level sections of the merged /api/v1/metrics document that a
#: provider must not shadow (the endpoint merges providers at the top
#: level, so a collision would silently clobber a core section).
RESERVED_PROVIDER_NAMES = frozenset({"counters", "timings", "trace", "faults"})


def register_provider(name: str, fn: Callable[[], dict]) -> None:
    """Register (or replace) a named evidence provider.  The metrics
    endpoint snapshots every provider per GET — e.g. the CURRENT run's
    ``ReplayDriver.stats()`` registers under ``"replay"`` (latest
    driver wins; one driver exists per ScenarioRunner run)."""
    if name in RESERVED_PROVIDER_NAMES:
        raise ValueError(
            f"provider name {name!r} shadows a core /api/v1/metrics section"
        )
    with _providers_lock:
        _providers[name] = fn


def provider_snapshots() -> dict[str, dict]:
    """All providers' current snapshots; a provider that raises reports
    its error instead of poisoning the metrics document."""
    with _providers_lock:
        items = list(_providers.items())
    out: dict[str, dict] = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:  # evidence endpoint must never 500
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


#: The process-global plane every span/event site checks.  ``KSIM_TRACE*``
#: configures it at import so subprocess children (bench rungs, the make
#: trace child) inherit tracing through the environment — the stdlib-only
#: bench parent never has to import this module.
TRACE = TracePlane()
TRACE.configure_from_env()


@atexit.register
def _export_at_exit() -> None:
    if TRACE.out_path and TRACE.active:
        try:
            TRACE.export_chrome(TRACE.out_path)
        except OSError:
            pass
