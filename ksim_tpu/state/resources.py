"""Typed accessors over Kubernetes resource JSON objects.

Resources are held as plain dicts in the exact JSON shape the Kubernetes API
(and the reference's snapshot format, simulator/snapshot/snapshot.go:33-42)
uses, so snapshot import/export round-trips byte-compatibly.  This module
provides the semantic accessors the scheduler needs, reproducing upstream
kube-scheduler lowering rules:

- pod resource requests: max(sum of containers, each init container) +
  overhead (upstream k8s.io/component-helpers resourcehelper.PodRequests)
- the scheduler's "non-zero" request defaulting used by scoring plugins:
  missing cpu => 100m, missing memory => 200MB decimal
  (upstream pkg/scheduler/util DefaultMilliCPURequest/DefaultMemoryRequest)
- CPU lowered to milli-units, everything else to integer units
  (upstream pkg/scheduler/framework/types.go Resource.Add)
"""

from __future__ import annotations

from typing import Any, Iterable

from ksim_tpu.state.quantity import parse_quantity

JSON = dict[str, Any]

# Upstream scheduler defaults for scoring "non-zero" requests
# (k8s.io/kubernetes/pkg/scheduler/util/pod_resources.go).
DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200MB

CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Always-checked resources in the Fit filter (upstream fit.go fitsRequest);
# the single definition shared by featurizer, kernels, and oracle.
BASE_RESOURCES = (CPU, MEMORY, EPHEMERAL_STORAGE)

# Well-known taint applied by cordoning (v1.TaintNodeUnschedulable).
UNSCHEDULABLE_TAINT = {
    "key": "node.kubernetes.io/unschedulable",
    "effect": "NoSchedule",
}


def name_of(obj: JSON) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: JSON) -> str:
    return obj.get("metadata", {}).get("namespace", "")


def labels_of(obj: JSON) -> dict[str, str]:
    return obj.get("metadata", {}).get("labels") or {}


def annotations_of(obj: JSON) -> dict[str, str]:
    return obj.get("metadata", {}).get("annotations") or {}


def namespaced_key(obj: JSON) -> str:
    ns = namespace_of(obj)
    return f"{ns}/{name_of(obj)}" if ns else name_of(obj)


def _lower(resource: str, qty_str: Any) -> int:
    """Lower one quantity to scheduler integer units (cpu -> milli)."""
    q = parse_quantity(qty_str)
    return q.milli_value if resource == CPU else q.value


def _resource_list(d: JSON | None) -> dict[str, int]:
    if not d:
        return {}
    return {r: _lower(r, v) for r, v in d.items()}


def _add_into(acc: dict[str, int], other: dict[str, int]) -> None:
    for r, v in other.items():
        acc[r] = acc.get(r, 0) + v


def _max_into(acc: dict[str, int], other: dict[str, int]) -> None:
    for r, v in other.items():
        if v > acc.get(r, 0):
            acc[r] = v


def pod_requests(pod: JSON, *, non_zero: bool = False) -> dict[str, int]:
    """Total scheduler-visible resource requests of a pod (memoized per
    object — callers must treat the returned dict as frozen).

    Mirrors upstream resourcehelper.PodRequests (k8s.io/component-helpers,
    v1.30 with sidecar support): sum of app containers, PLUS restartable
    (restartPolicy: Always) init containers which add to the running total;
    each non-restartable init container's requirement is its own requests
    plus the sidecars declared before it, and the element-wise max of those
    is taken against the running total; plus pod overhead.
    With ``non_zero=True``, applies the scoring-path defaulting for
    containers missing cpu/memory requests (NonMissingContainerRequests in
    upstream noderesources/resource_allocation.go calculatePodResourceRequest).
    """
    from ksim_tpu.state import objcache

    key = ("preq", objcache.ref_id(pod), non_zero)
    hit = objcache.get(key)
    if hit is not objcache.MISS:
        return hit
    return objcache.put(key, _pod_requests(pod, non_zero))


def _pod_requests(pod: JSON, non_zero: bool) -> dict[str, int]:
    spec = pod.get("spec", {})

    def container_req(c: JSON) -> dict[str, int]:
        req = _resource_list((c.get("resources") or {}).get("requests"))
        if non_zero:
            req.setdefault(CPU, DEFAULT_MILLI_CPU_REQUEST)
            req.setdefault(MEMORY, DEFAULT_MEMORY_REQUEST)
        return req

    total: dict[str, int] = {}
    for c in spec.get("containers") or []:
        _add_into(total, container_req(c))
    restartable_sum: dict[str, int] = {}
    init_max: dict[str, int] = {}
    for c in spec.get("initContainers") or []:
        req = container_req(c)
        if c.get("restartPolicy") == "Always":
            _add_into(total, req)
            _add_into(restartable_sum, req)
        else:
            tmp = dict(req)
            _add_into(tmp, restartable_sum)
            _max_into(init_max, tmp)
    _max_into(total, init_max)
    overhead = _resource_list(spec.get("overhead"))
    _add_into(total, overhead)
    return total


def node_allocatable(node: JSON) -> dict[str, int]:
    """Node allocatable in scheduler units; falls back to capacity.
    Memoized per node object (returned dict is frozen) so the
    featurizer's lower() rows can memoize on the dict's identity."""
    from ksim_tpu.state import objcache

    def build() -> dict[str, int]:
        status = node.get("status", {})
        alloc = status.get("allocatable") or status.get("capacity") or {}
        return _resource_list(alloc)

    return objcache.cached("nodealloc", node, build)


def node_unschedulable(node: JSON) -> bool:
    return bool(node.get("spec", {}).get("unschedulable", False))


def node_taints(node: JSON) -> list[JSON]:
    return node.get("spec", {}).get("taints") or []


def pod_tolerations(pod: JSON) -> list[JSON]:
    return pod.get("spec", {}).get("tolerations") or []


def pod_node_name(pod: JSON) -> str:
    return pod.get("spec", {}).get("nodeName", "") or ""


def pod_is_scheduled(pod: JSON) -> bool:
    return bool(pod_node_name(pod))


def pod_priority(pod: JSON) -> int:
    return int(pod.get("spec", {}).get("priority") or 0)


def toleration_tolerates(tol: JSON, taint: JSON) -> bool:
    """Upstream v1.Toleration.ToleratesTaint semantics."""
    if tol.get("effect") and tol.get("effect") != taint.get("effect"):
        return False
    if tol.get("key") and tol.get("key") != taint.get("key"):
        return False
    op = tol.get("operator") or "Equal"
    if op == "Exists":
        return True
    if op == "Equal":
        return (tol.get("value") or "") == (taint.get("value") or "")
    return False


def tolerations_tolerate_taint(tolerations: Iterable[JSON], taint: JSON) -> bool:
    return any(toleration_tolerates(t, taint) for t in tolerations)


def untolerated_taint(
    taints: Iterable[JSON],
    tolerations: Iterable[JSON],
    effects: tuple[str, ...] = ("NoSchedule", "NoExecute"),
) -> JSON | None:
    """First taint with an effect in ``effects`` that no toleration matches."""
    tolerations = list(tolerations)
    for taint in taints:
        if taint.get("effect") not in effects:
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint
    return None
