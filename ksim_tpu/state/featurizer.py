"""Snapshot -> fixed-shape device tensors.

This is the host->TPU boundary of the framework: the analogue of the
reference's NodeInfo/PodInfo construction in the upstream scheduler cache
(which the wrapped plugins consume per-(pod,node) call,
reference simulator/scheduler/plugin/wrappedplugin.go:420-548).  Everything
the batched Filter/Score kernels need is lowered here once per snapshot:

- **Resource axis.** The tracked resource set is cpu, memory,
  ephemeral-storage plus any extended resources present in the snapshot.
  ``pods`` capacity is a separate scalar ("Too many pods" check).
- **Exact unit scaling.** Kube-scheduler does int64 math; TPU integer math
  is int32.  Each resource r gets a unit u_r = gcd of every observed value
  of r, and all values are stored as value/u_r.  Integer-division score
  formulas like ``(c-r)*100//c`` are ratios of the raw values, so dividing
  numerator and denominator by the same u_r leaves every result bit-exact.
  If the scaled values could still overflow ``int32`` through the ``*100``
  in the score formula the featurizer falls back to lossy scaling and
  records ``exact=False`` (callers can then route parity-critical runs to
  the int64 path / host oracle).
- **Padding + bucketing.**  Pod and node counts are padded up to
  bucketed shapes (powers of two, with a 3/4 step in the >= 8192-pow2
  octaves — see ``bucket_size``) so recompiles are bounded (SURVEY.md
  section 7 hard part 4); ``valid`` masks carry the true extents.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from ksim_tpu.state.resources import (
    BASE_RESOURCES,
    UNSCHEDULABLE_TAINT,
    CPU,
    JSON,
    MEMORY,
    EPHEMERAL_STORAGE,
    PODS,
    labels_of,
    name_of,
    namespaced_key,
    node_allocatable,
    node_unschedulable,
    pod_is_scheduled,
    pod_node_name,
    pod_requests,
    pod_tolerations,
    tolerations_tolerate_taint,
)

# Largest per-resource scaled value that keeps v*100 (MaxNodeScore) in int32.
MAX_EXACT_SCALED = (2**31 - 1) // 128

# The tracked-resource prefix is BASE_RESOURCES (state/resources.py);
# extended resources are appended in sorted order.


def bucket_size(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (>= minimum) — with a 3/4 step
    once the pow2 reaches 8192 (…, 2048, 4096, 6144, 8192, 12288,
    16384, …).

    Pure powers of two waste up to half the compiled program's work on
    padding (5000 pods -> 8192 meant the headline scan burned 39% of
    its FLOPs on masked rows; 10k x 5k burned 44% across both axes).
    The extra bucket exists only at >= 8192 pow2s, so churn-scale
    shapes (pods capped per pass, vocabularies reset-valved at 4096,
    thousands of nodes) keep the exact old ladder — no new recompile
    boundaries there — and every 3/4 step is divisible by 2048, so
    dp/tp mesh sharding still divides evenly."""
    if n <= minimum:
        return minimum
    p = 1 << (n - 1).bit_length()
    if p >= 8192 and n <= (p * 3) // 4:
        return (p * 3) // 4
    return p


def vocab_pad(n: int, minimum: int = 8) -> int:
    """Bucket for a VOCABULARY axis (the ``bucket_size`` ladder): churn
    replay adds and removes vocab entries constantly, and unbucketed
    vocab shapes would force an XLA recompile on nearly every step (the
    pod/node axes are bucketed the same way)."""
    return bucket_size(max(n, 1), minimum)


@dataclass
class NodeTensors:
    """Per-node device-ready arrays, shape [N] or [N, R]."""

    names: list[str]
    allocatable: np.ndarray  # int32 [N, R] scaled
    allowed_pods: np.ndarray  # int32 [N]
    requested: np.ndarray  # int32 [N, R] from already-bound pods
    nonzero_requested: np.ndarray  # int32 [N, R] scoring-path accumulation
    pod_count: np.ndarray  # int32 [N]
    unschedulable: np.ndarray  # bool [N]
    valid: np.ndarray  # bool [N]

    @property
    def count(self) -> int:
        return len(self.names)

    @property
    def padded(self) -> int:
        return self.valid.shape[0]


@dataclass
class PodTensors:
    """Per-pod device-ready arrays, shape [P] or [P, R]."""

    keys: list[str]  # namespace/name
    requests: np.ndarray  # int32 [P, R] scaled (Fit filter path)
    nonzero_requests: np.ndarray  # int32 [P, R] scaled (scoring path)
    valid: np.ndarray  # bool [P]
    tolerates_unschedulable: np.ndarray  # bool [P]
    has_requests: np.ndarray  # bool [P] (fitsRequest early-exit predicate)
    index: np.ndarray  # int32 [P] == arange (row into per-pod aux arrays)

    @property
    def count(self) -> int:
        return len(self.keys)


@dataclass
class FeaturizedSnapshot:
    """Everything the batched kernels need, plus host-side decode tables."""

    resources: tuple[str, ...]  # the R axis
    units: dict[str, int]  # resource -> divisor used in scaling
    exact: bool  # int32 math is bit-exact vs int64
    nodes: NodeTensors
    pods: PodTensors
    aux: dict[str, Any] = field(default_factory=dict)  # plugin extras

    def resource_index(self, r: str) -> int:
        return self.resources.index(r)


def _gcd_unit(values: Sequence[int]) -> int:
    g = 0
    for v in values:
        g = math.gcd(g, v)
    return g or 1


class Featurizer:
    """Lower a snapshot (lists of pod/node JSON objects) to tensors."""

    def __init__(
        self,
        *,
        node_bucket_min: int | None = None,
        pod_bucket_min: int | None = None,
        interpod_hard_weight: int | None = None,
        extra_encoders: "dict[str, Any] | None" = None,
        added_affinity: "JSON | None" = None,
        spread_defaults: "tuple | None" = None,
    ) -> None:
        """``extra_encoders`` maps aux key -> fn(nodes, queue_pods,
        n_padded, p_padded) -> dataclass-with-AXES — the hook out-of-tree
        plugins use to ship their own tensors to the device (the sample
        NodeNumber / data-provider plugins ride this).  ``added_affinity``
        is the profile's NodeAffinityArgs.addedAffinity (upstream
        node_affinity.go addedNodeSelector/addedPrefSchedTerms)."""
        if interpod_hard_weight is None:
            from ksim_tpu.state.interpod import DEFAULT_HARD_POD_AFFINITY_WEIGHT

            interpod_hard_weight = DEFAULT_HARD_POD_AFFINITY_WEIGHT
        self._node_bucket_min = node_bucket_min if node_bucket_min else 8
        self._pod_bucket_min = pod_bucket_min if pod_bucket_min else 8
        self._interpod_hard_weight = interpod_hard_weight
        self._extra_encoders = dict(extra_encoders or {})
        self._added_affinity = added_affinity
        # PodTopologySpreadArgs default constraints (List defaulting, or
        # the upstream systemDefaultConstraints for System) — inert in
        # the snapshot model (see encoding.default_spread_selector) but
        # threaded so the behavior is upstream-shaped.
        self._spread_defaults = spread_defaults
        # Incremental bound-pod aggregation across featurizations of the
        # SAME evolving cluster (state/boundagg.py): node-name slots keep
        # the node axis stable under churn, and the additive aggregates
        # update by delta instead of re-walking every bound pod.  A fresh
        # instance behaves exactly like the one-shot path (slot order =
        # first-seen order = the caller's order).
        from ksim_tpu.state.boundagg import NodeSlots

        self._slots = NodeSlots()
        # Slot churn applied through advance_slots() between featurize
        # calls (the device-resident replay rolls node history forward
        # step by step without featurizing); merged into the next
        # featurize's changed-slot set so family repair still sees it.
        self._pending_changed: set[int] = set()
        self._agg: dict[str, Any] = {}
        # Shared per-pass bound-set diff (see boundagg.sync_family): one
        # O(bound) comparison per pass instead of one per family.
        self._prev_bound: dict[int, JSON] = {}
        self._bound_gen = 0
        # Bound pods carrying volumes, maintained from the diff — the
        # volumes fast path needs "is ANY bound pod using volumes", and
        # re-scanning 15k+ bound pods per pass was the single largest
        # steady-state featurize cost.
        self._bound_vol_count = 0
        # O(delta) evidence counters: per-pod base-row computations that
        # actually RAN vs. ones served from the identity memo.  A caller
        # with an identity-stable queue (the replay lower-cache keeps
        # surviving universe pods' objects alive across segments) should
        # see ``pod_rows_built`` grow with its per-window object churn,
        # not with the universe size — the counter the bench /
        # ``make lock-check`` O(delta) guard reads (docs/churn_floor.md
        # "Incremental lowering + pipelined executor").
        self.pod_rows_built = 0
        self.pod_rows_reused = 0
        self.featurize_passes = 0

    def slot_names(self) -> list[str]:
        """The current node-slot order, lowest slot first — the carry a
        segment checkpoint records so ``seed_slots`` can reinstall it on
        a restored run (scheduler/service.py ``checkpoint_carries``)."""
        return list(self._slots._names)

    def seed_slots(self, names: Sequence[str]) -> None:
        """Install a checkpoint-recorded node-slot order on a FRESH
        featurizer (job-plane incremental resume — see
        ``boundagg.NodeSlots.seed``).  Every seeded slot is queued as
        changed so the first featurize repairs families against the
        live objects; on a fresh instance that repair is the from-
        scratch rebuild it would have done anyway."""
        self._slots.seed(names)
        self._pending_changed |= set(range(len(names)))

    def advance_slots(self, nodes: Sequence[JSON]) -> None:
        """Advance the persistent node-slot history WITHOUT featurizing.

        The device-resident replay (engine/replay.py) schedules whole
        step segments off-host; between those steps this featurizer never
        runs, but its slot assignment must still follow every node
        delete/create so a later per-pass fallback sees the exact order
        the pure per-pass history would have produced.  Changed slots
        accumulate and merge into the next featurize's repair set."""
        _ordered, changed = self._slots.sync(list(nodes))
        self._pending_changed |= changed

    def featurize(
        self,
        nodes: Sequence[JSON],
        pods: Sequence[JSON],
        *,
        queue_pods: Sequence[JSON] = (),
        bound_pods: "Sequence[JSON] | None" = None,
        namespaces: Sequence[JSON] = (),
        pvs: Sequence[JSON] = (),
        pvcs: Sequence[JSON] = (),
        storage_classes: Sequence[JSON] = (),
    ) -> FeaturizedSnapshot:
        """``pods`` are existing cluster pods (bound ones charge their node);
        ``queue_pods`` are the pods to schedule (the pod axis P);
        ``bound_pods``, when given, are the node-bound pods (spec.nodeName
        set; callers with an indexed store pass
        ``store.pods_with_node()`` to skip the O(all pods) split —
        phase filtering still happens here);
        ``namespaces`` feed namespaceSelector matching (InterPodAffinity);
        ``pvs``/``pvcs``/``storage_classes`` feed the volume plugins."""
        from ksim_tpu.state import objcache

        # Safe point for memo-table size enforcement: no memo key is in
        # flight here (see objcache.maybe_flush).
        objcache.maybe_flush()

        from ksim_tpu.state.boundagg import sync_family

        sched_pods = list(queue_pods) if queue_pods else [
            p for p in pods if not pod_is_scheduled(p)
        ]
        bound_src = pods if bound_pods is None else bound_pods
        bound_pods = [
            p
            for p in bound_src
            if pod_is_scheduled(p)
            and (p.get("status", {}).get("phase") not in ("Succeeded", "Failed"))
        ]

        # Stable node slots: churn must not shift the node axis under the
        # incremental aggregates.  For a fresh featurizer this is the
        # caller's order.
        nodes, changed_slots = self._slots.sync(nodes)
        if self._pending_changed:
            changed_slots = changed_slots | self._pending_changed
            self._pending_changed = set()
        bound_map = {id(p): p for p in bound_pods}
        # Publish the shared arrival/departure diff for every family this
        # pass syncs (holding the previous map's pod refs keeps ids from
        # being recycled while they can still appear in a diff).
        prev = self._prev_bound
        self._bound_gen += 1
        added = [pid for pid in bound_map if pid not in prev]
        removed = [pid for pid in prev if pid not in bound_map]
        self._agg["__diff__"] = {
            "gen": self._bound_gen,
            "added": added,
            "removed": removed,
        }
        from ksim_tpu.state.volumes import _pod_has_volumes

        for pid in added:
            self._bound_vol_count += _pod_has_volumes(bound_map[pid])
        for pid in removed:
            self._bound_vol_count -= _pod_has_volumes(prev[pid])
        self._prev_bound = bound_map

        node_alloc = [node_allocatable(n) for n in nodes]
        pod_reqs = [pod_requests(p) for p in sched_pods]
        pod_nz_reqs = [pod_requests(p, non_zero=True) for p in sched_pods]

        # Bound pods' raw request values as an incrementally-maintained
        # multiset per resource: the resource axis and exact gcd units
        # need every value that enters math, without an O(bound) walk.
        def _resvals_record(p: JSON):
            pairs = []
            for non_zero in (False, True):
                for r, v in pod_requests(p, non_zero=non_zero).items():
                    if v:
                        pairs.append((r, v))
            return (-1, tuple(pairs))

        def _resvals_apply(counters: dict, rec, sign: int) -> None:
            for r, v in rec[1]:
                c = counters.setdefault(r, {})
                nv = c.get(v, 0) + sign
                if nv:
                    c[v] = nv
                else:
                    del c[v]
                    if not c:
                        del counters[r]

        bound_vals: dict[str, dict[int, int]] = sync_family(
            self._agg,
            "resvals",
            (),
            bound_map,
            set(),  # node-independent
            make_arrays=dict,
            record_of=_resvals_record,
            apply=_resvals_apply,
        )

        # Resource axis: base prefix + extended resources seen anywhere.
        seen: set[str] = set()
        for d in (*node_alloc, *pod_reqs):
            seen.update(d.keys())
        seen.update(bound_vals.keys())
        seen.discard(PODS)
        extended = sorted(seen - set(BASE_RESOURCES))
        resources = BASE_RESOURCES + tuple(extended)
        ridx = {r: i for i, r in enumerate(resources)}
        R = len(resources)
        exact = True
        if R > 29:
            # Reason bits past bit 30 saturate into a shared bit (see
            # plugins/noderesources.py); decoded reasons are then ambiguous.
            exact = False

        # Exact gcd units per resource across every value that enters math.
        units: dict[str, int] = {}
        for r in resources:
            vals = [d.get(r, 0) for d in (*node_alloc, *pod_reqs, *pod_nz_reqs)]
            vals = [v for v in vals if v]
            vals.extend(bound_vals.get(r, ()))
            unit = _gcd_unit(vals)
            max_scaled = max((v // unit for v in vals), default=0)
            if max_scaled > MAX_EXACT_SCALED:
                # Lossy fallback: keep magnitudes bounded, mark inexact.
                unit = unit * -(-max_scaled // MAX_EXACT_SCALED)
                exact = False
            units[r] = unit

        # The requests dicts are memoized per pod object (pod_requests),
        # so lowered rows can be memoized on the dict's identity as long
        # as the unit scaling they were lowered with is part of the key.
        units_token = (resources, tuple(units[r] for r in resources))

        def lower(d: dict[str, int]) -> np.ndarray:
            key = ("lower", objcache.ref_id(d), units_token)
            hit = objcache.get(key)
            if hit is not objcache.MISS:
                return hit
            row = np.zeros(R, dtype=np.int64)
            for r, v in d.items():
                i = ridx.get(r)
                if i is not None:
                    u = units[r]
                    row[i] = v // u if v % u == 0 else -(-v // u)
            return objcache.put(key, row)

        N, P = len(nodes), len(sched_pods)
        NP, PP = bucket_size(N, self._node_bucket_min), bucket_size(P, self._pod_bucket_min)

        def build_node_arrays():
            alloc = np.zeros((NP, R), dtype=np.int32)
            allowed_pods = np.zeros(NP, dtype=np.int32)
            unsched = np.zeros(NP, dtype=bool)
            nvalid = np.zeros(NP, dtype=bool)
            node_names = [name_of(n) for n in nodes]
            for i, n in enumerate(nodes):
                alloc[i] = lower(node_alloc[i])
                allowed_pods[i] = node_alloc[i].get(PODS, 0)
                unsched[i] = node_unschedulable(n)
                nvalid[i] = True
            return alloc, allowed_pods, unsched, nvalid, node_names

        # Family-cached on the exact node objects + unit scaling: under
        # churn the node list and units are stable most passes, so the
        # 2k-iteration lowering loop collapses to one dict hit.
        alloc, allowed_pods, unsched, nvalid, node_names = objcache.cached_seq(
            "feat_nodes", nodes, build_node_arrays, units_token, NP
        )
        node_index = self._slots.slot_of

        # Per-node request sums from bound pods, maintained by delta.
        # Masters accumulate in int64: per-value bounds don't bound the
        # SUM over bound pods; clamp (and drop exactness) on the copies
        # only if a sum overflows.
        def _req_record(p: JSON):
            ni = node_index.get(pod_node_name(p))
            if ni is None or ni >= N:
                return None
            return (
                ni,
                (lower(pod_requests(p)), lower(pod_requests(p, non_zero=True))),
            )

        def _req_apply(arrays, rec, sign: int) -> None:
            ni, (row, nzrow) = rec
            if sign > 0:
                arrays["req"][ni] += row
                arrays["nz"][ni] += nzrow
                arrays["cnt"][ni] += 1
            else:
                arrays["req"][ni] -= row
                arrays["nz"][ni] -= nzrow
                arrays["cnt"][ni] -= 1

        reqagg = sync_family(
            self._agg,
            "requested",
            (units_token, NP),
            bound_map,
            changed_slots,
            make_arrays=lambda: {
                "req": np.zeros((NP, R), dtype=np.int64),
                "nz": np.zeros((NP, R), dtype=np.int64),
                "cnt": np.zeros(NP, dtype=np.int32),
            },
            record_of=_req_record,
            apply=_req_apply,
        )
        requested = reqagg["req"].copy()
        nz_requested = reqagg["nz"].copy()
        pod_count = reqagg["cnt"].copy()

        if requested.max(initial=0) > MAX_EXACT_SCALED or nz_requested.max(initial=0) > MAX_EXACT_SCALED:
            exact = False
            requested = np.minimum(requested, MAX_EXACT_SCALED)
            nz_requested = np.minimum(nz_requested, MAX_EXACT_SCALED)
        requested = requested.astype(np.int32)
        nz_requested = nz_requested.astype(np.int32)

        preq = np.zeros((PP, R), dtype=np.int32)
        pnz = np.zeros((PP, R), dtype=np.int32)
        pvalid = np.zeros(PP, dtype=bool)
        ptol = np.zeros(PP, dtype=bool)
        phas = np.zeros(PP, dtype=bool)
        base_set = set(BASE_RESOURCES)

        self.featurize_passes += 1

        def pod_base(p: JSON, j: int):
            """One memo entry bundling the pod's base-row pieces — a
            saturated churn pass re-featurizes ~1k unchanged pods, and
            one lookup per pod beats four."""
            key = ("podbase", objcache.ref_id(p), units_token)
            hit = objcache.get(key)
            if hit is not objcache.MISS:
                self.pod_rows_reused += 1
                return hit
            self.pod_rows_built += 1
            reqs = pod_reqs[j]
            # Upstream fitsRequest early-exit predicate: base requests all
            # zero AND no scalar-resource key present (a zero-valued
            # extended-resource key still defeats the early return).
            bundle = (
                lower(reqs),
                lower(pod_nz_reqs[j]),
                tolerations_tolerate_taint(pod_tolerations(p), UNSCHEDULABLE_TAINT),
                any(reqs.get(r, 0) for r in BASE_RESOURCES)
                or any(k not in base_set and k != PODS for k in reqs),
            )
            return objcache.put(key, bundle)

        for j, p in enumerate(sched_pods):
            preq[j], pnz[j], ptol[j], phas[j] = pod_base(p, j)
            pvalid[j] = True

        from ksim_tpu.state.encoding import (
            encode_affinity,
            encode_taints,
            encode_topology_spread,
        )
        from ksim_tpu.state.extras import (
            encode_image_locality,
            encode_node_name,
            encode_node_ports,
        )
        from ksim_tpu.state.interpod import encode_inter_pod
        from ksim_tpu.state.volumes import encode_volumes

        aux = {
            "affinity": encode_affinity(
                nodes, sched_pods, NP, PP, added_affinity=self._added_affinity
            ),
            "taints": encode_taints(nodes, sched_pods, NP, PP),
            "spread": encode_topology_spread(
                nodes, sched_pods, bound_pods, NP, PP,
                agg=self._agg, bound_map=bound_map,
                changed_slots=changed_slots, slot_of=node_index,
                default_constraints=self._spread_defaults,
            ),
            "interpod": encode_inter_pod(
                nodes, sched_pods, bound_pods, namespaces, NP, PP,
                hard_weight=self._interpod_hard_weight,
                agg=self._agg, bound_map=bound_map,
                changed_slots=changed_slots, slot_of=node_index,
            ),
            "nodename": encode_node_name(nodes, sched_pods, PP),
            "nodeports": encode_node_ports(nodes, sched_pods, bound_pods, NP, PP),
            "imagelocality": encode_image_locality(nodes, sched_pods, NP, PP),
            "volumes": encode_volumes(
                nodes, sched_pods, bound_pods, pvs, pvcs, storage_classes, NP, PP,
                bound_volume_free=self._bound_vol_count == 0,
            ),
        }
        for key, encoder in self._extra_encoders.items():
            aux[key] = encoder(nodes, sched_pods, NP, PP)

        return FeaturizedSnapshot(
            resources=resources,
            units=units,
            exact=exact,
            aux=aux,
            nodes=NodeTensors(
                names=node_names,
                allocatable=alloc,
                allowed_pods=allowed_pods,
                requested=requested,
                nonzero_requested=nz_requested,
                pod_count=pod_count,
                unschedulable=unsched,
                valid=nvalid,
            ),
            pods=PodTensors(
                keys=[namespaced_key(p) for p in sched_pods],
                requests=preq,
                nonzero_requests=pnz,
                valid=pvalid,
                tolerates_unschedulable=ptol,
                has_requests=phas,
                index=np.arange(PP, dtype=np.int32),
            ),
        )
