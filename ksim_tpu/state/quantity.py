"""Kubernetes resource.Quantity parsing with exact integer semantics.

The reference (and upstream kube-scheduler) does all resource math on
``resource.Quantity`` values lowered to int64: ``MilliValue()`` for CPU and
``Value()`` for memory/storage/pods (upstream
k8s.io/kubernetes/pkg/scheduler/framework/types.go, Resource.Add).  Bit-exact
score parity (BASELINE.md config 4) requires reproducing that lowering
exactly, so quantities are parsed to exact rationals (suffix grammar from
apimachinery/pkg/api/resource/quantity.go) and rounded the way Go does:
``Value()``/``MilliValue()`` round *up* (away from zero) to the nearest
integer at the requested scale.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from fractions import Fraction
from functools import lru_cache

# Decimal SI suffixes (powers of 10) and binary suffixes (powers of 1024).
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}
_BINARY_SUFFIXES = {
    "Ki": Fraction(1024),
    "Mi": Fraction(1024**2),
    "Gi": Fraction(1024**3),
    "Ti": Fraction(1024**4),
    "Pi": Fraction(1024**5),
    "Ei": Fraction(1024**6),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:(?P<suffix>[numkMGTPE]|[KMGTPE]i)|[eE](?P<exp>[+-]?[0-9]+))?$"
)


def _ceil_div(a: int, b: int) -> int:
    return -((-a) // b)


@dataclass(frozen=True, slots=True)
class Quantity:
    """An exact rational resource quantity."""

    raw: Fraction

    @property
    def value(self) -> int:
        """Integer value, rounded up — matches Go Quantity.Value()."""
        return self.scaled(1)

    @property
    def milli_value(self) -> int:
        """Milli-units, rounded up — matches Go Quantity.MilliValue()."""
        return self.scaled(Fraction(1, 1000))

    def scaled(self, unit: Fraction | int) -> int:
        """Number of ``unit``-sized chunks, rounded up (away from zero).
        Cached — featurization rescales the same handful of distinct
        (value, unit) pairs for every pod every pass."""
        return _scaled_cached(self.raw, unit)

    @property
    def is_integer(self) -> bool:
        return self.raw.denominator == 1

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.raw + other.raw)

    def __str__(self) -> str:  # canonical-ish rendering for serialization
        if self.raw.denominator == 1:
            return str(self.raw.numerator)
        m = self.raw * 1000
        if m.denominator == 1:
            return f"{m.numerator}m"
        n = self.raw * 10**9
        return f"{_ceil_div(n.numerator, n.denominator)}n"


@lru_cache(maxsize=65536)
def _scaled_cached(raw: Fraction, unit: Fraction | int) -> int:
    q = raw / Fraction(unit)
    if q >= 0:
        return _ceil_div(q.numerator, q.denominator)
    return -_ceil_div(-q.numerator, q.denominator)


def parse_quantity(s: str | int | float | Quantity) -> Quantity:
    """Parse a Kubernetes quantity string ("100m", "2Gi", "1.5", "1e3").
    Cached — clusters repeat a handful of distinct quantity strings, and
    featurization parses them for every pod every scheduling pass."""
    if isinstance(s, Quantity):
        return s
    return _parse_quantity_cached(s)


@lru_cache(maxsize=65536)
def _parse_quantity_cached(s: str | int | float) -> Quantity:
    if isinstance(s, int):
        return Quantity(Fraction(s))
    if isinstance(s, float):
        return Quantity(Fraction(s).limit_denominator(10**9))
    m = _QUANTITY_RE.match(s.strip())
    if m is None:
        raise ValueError(f"invalid quantity: {s!r}")
    num = Fraction(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    suffix = m.group("suffix")
    exp = m.group("exp")
    if exp is not None:
        num *= Fraction(10) ** int(exp)
    elif suffix:
        if suffix in _BINARY_SUFFIXES:
            num *= _BINARY_SUFFIXES[suffix]
        else:
            num *= _DECIMAL_SUFFIXES[suffix]
    return Quantity(num)


ZERO = Quantity(Fraction(0))
