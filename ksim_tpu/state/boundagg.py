"""Incremental bound-pod aggregation for churn-scale featurization.

Featurizing a snapshot walks every BOUND pod to build additive node-space
aggregates (requested-resource sums, inter-pod-affinity domain counts,
topology-spread selector counts).  Under churn replay that walk is the
scaling wall: the bound population reaches 10k+ while only ~200 pods
change per scheduling pass, so re-aggregating from scratch costs
O(bound) Python work per pass (measured 0.6s/pass at 11k bound pods —
more than the TPU compute it feeds).

This module lets a persistent ``Featurizer`` maintain those aggregates
across passes:

- ``NodeSlots`` pins each node NAME to a stable position on the node
  axis so that node churn does not shift every other node's index
  (deletion swap-removes: the last slot's node moves into the freed
  slot, so exactly two slots change).  For a fresh instance the order is
  first-seen order, i.e. identical to the caller's list.
- ``sync_family`` maintains one aggregate: per-pod contribution records
  applied additively (+1 on arrival, -1 on departure), with per-slot
  repair when a slot's node changed (drained node, replaced object) and
  a full rebuild whenever the family's validity token changes (vocab
  growth, unit rescale, axis resize).

Correctness contract: ``apply(arrays, rec, +1)`` followed by
``apply(arrays, rec, -1)`` must be a no-op, and ``record_of(pod)`` must
be a pure function of (pod content, the family token, current node
slots).  The equivalence tests (tests/test_boundagg.py) replay random
mutation sequences and assert a persistent featurizer's engine-visible
outputs match a fresh featurizer's.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

from ksim_tpu.state.resources import JSON, name_of

__all__ = ["NodeSlots", "sync_family"]


class NodeSlots:
    """Persistent node-name -> axis-slot assignment with swap-remove."""

    def __init__(self) -> None:
        self.slot_of: dict[str, int] = {}
        self._names: list[str] = []
        # The node OBJECT last seen per slot (a strong ref, compared by
        # identity): comparing bare id() values would miss a replacement
        # whose new dict recycled the old dict's address.
        self._objs: list[JSON] = []

    def seed(self, names: Sequence[str]) -> None:
        """Install a recorded name order VERBATIM (checkpoint restore,
        ksim_tpu/jobs/manager.py).  Slot order is scheduling-visible —
        selectHost breaks score ties by lowest slot index, and the
        evolved swap-remove order diverges from first-seen order — so a
        resumed run must start from the order the interrupted run had,
        not rediscover it from the caller's list.  Per-slot object refs
        reset to fresh sentinels: the next ``sync`` sees an identity
        mismatch on every slot and marks them all changed, so the
        additive families repair/rebuild against real objects (a fresh
        featurizer rebuilds from scratch anyway — the seed trades one
        full repair for the exact ORDER)."""
        self.slot_of = {nm: i for i, nm in enumerate(names)}
        self._names = list(names)
        self._objs = [{} for _ in names]

    def sync(self, nodes: Sequence[JSON]) -> tuple[list[JSON], set[int]]:
        """Update the assignment for the current node set.

        Returns (nodes reordered to slot order, slots whose occupant
        changed since the previous call — by name or by object).
        """
        by_name = {name_of(n): n for n in nodes}
        changed: set[int] = set()

        # Deletions: swap-remove, highest slot first so the swap source
        # is never itself a pending deletion's stale position.
        gone = [s for nm, s in self.slot_of.items() if nm not in by_name]
        for s in sorted(gone, reverse=True):
            nm = self._names[s]
            last = len(self._names) - 1
            del self.slot_of[nm]
            if s != last:
                moved = self._names[last]
                self._names[s] = moved
                self._objs[s] = self._objs[last]
                self.slot_of[moved] = s
                changed.add(s)
            self._names.pop()
            self._objs.pop()
            changed.discard(last)
            changed.add(last)  # slot vanished (or shrank away)

        # Additions + object changes.
        for nm, n in by_name.items():
            s = self.slot_of.get(nm)
            if s is None:
                s = len(self._names)
                self.slot_of[nm] = s
                self._names.append(nm)
                self._objs.append(n)
                changed.add(s)
            elif self._objs[s] is not n:
                self._objs[s] = n
                changed.add(s)

        ordered = [by_name[nm] for nm in self._names]
        # Slots past the current end stay in ``changed``: records pinned
        # to a vanished slot index must still be repaired.
        return ordered, changed


def sync_family(
    state: dict,
    name: str,
    token: Any,
    bound_map: dict[int, JSON],
    changed_slots: set[int],
    *,
    make_arrays: Callable[[], Any],
    record_of: Callable[[JSON], "tuple[int, Any] | None"],
    apply: Callable[[Any, Any, int], None],
) -> Any:
    """Maintain one additive aggregate over the bound-pod population.

    ``bound_map``: id(pod) -> pod for the CURRENT bound set (caller
    builds it once per pass and shares it across families).
    ``record_of``: pod -> (slot, contribution) or None (no contribution;
    e.g. the pod's node does not exist).
    ``apply``: apply a contribution to the arrays with sign +1/-1.

    Returns the family's arrays (the live master — callers must treat
    them as read-only and copy before handing them to the engine).
    """
    diff = state.get("__diff__")
    fam = state.get(name)
    if fam is not None and fam["token"] != token:
        fam = None
    if fam is None:
        arrays = make_arrays()
        records: dict[int, tuple[JSON, Any]] = {}
        by_slot: dict[int, set[int]] = {}
        nones: set[int] = set()
        for pid, p in bound_map.items():
            rec = record_of(p)
            records[pid] = (p, rec)
            if rec is None:
                nones.add(pid)
            else:
                apply(arrays, rec, +1)
                by_slot.setdefault(rec[0], set()).add(pid)
        state[name] = {
            "token": token,
            "records": records,
            "by_slot": by_slot,
            "nones": nones,
            "arrays": arrays,
            "gen": diff["gen"] if diff else None,
        }
        return arrays

    records = fam["records"]
    by_slot = fam["by_slot"]
    nones = fam["nones"]
    arrays = fam["arrays"]

    def _drop(pid: int) -> None:
        _p, rec = records.pop(pid)
        if rec is None:
            nones.discard(pid)
        else:
            apply(arrays, rec, -1)
            peers = by_slot.get(rec[0])
            if peers is not None:
                peers.discard(pid)
                if not peers:
                    del by_slot[rec[0]]

    def _add(pid: int, p: JSON) -> None:
        rec = record_of(p)
        records[pid] = (p, rec)
        if rec is None:
            nones.add(pid)
        else:
            apply(arrays, rec, +1)
            by_slot.setdefault(rec[0], set()).add(pid)

    # 1+3. Departures and arrivals.  When the caller published a shared
    # per-pass diff ("__diff__" in the state dict, written once by the
    # featurizer) and this family was synced on the immediately preceding
    # pass, consume the diff directly — O(changed) instead of two
    # O(bound) scans per family per pass (the dict-walk cost dominated
    # saturated churn-replay host time).  Any gap in the family's sync
    # history (fresh family, skipped pass) falls back to the full scans.
    if (
        diff is not None
        and fam.get("gen") is not None
        and fam["gen"] == diff["gen"] - 1
    ):
        departures = [pid for pid in diff["removed"] if pid in records]
        arrivals = [(pid, bound_map[pid]) for pid in diff["added"]]
    else:
        departures = [pid for pid in records if pid not in bound_map]
        arrivals = None
    for pid in departures:
        _drop(pid)
    # 2. Slot repairs: pods whose node changed (or vanished/moved), plus
    #    previously node-less pods whenever any slot changed (their node
    #    may just have appeared).
    if changed_slots:
        repair = set()
        for s in changed_slots:
            repair |= by_slot.get(s, set())
        repair |= nones
        for pid in repair:
            if pid in bound_map:
                p = records[pid][0]
                _drop(pid)
                _add(pid, p)
    # 3. Arrivals.
    if arrivals is not None:
        for pid, p in arrivals:
            if pid not in records:
                _add(pid, p)
    else:
        for pid, p in bound_map.items():
            if pid not in records:
                _add(pid, p)
    if diff is not None:
        fam["gen"] = diff["gen"]
    return arrays
