"""Kubernetes label-selector and node-selector matching semantics.

Pure-Python (host-side) implementations of the matching rules used across
the snapshot service (label-selector filtered export, reference
simulator/snapshot/snapshot.go:104-140) and the affinity-family plugins.
The batched plugins encode these same rules as tensor ops via the
featurizer's vocabularies; these functions are the parity oracle.

Semantics mirror k8s.io/apimachinery/pkg/apis/meta/v1 LabelSelectorAsSelector
and k8s.io/component-helpers/scheduling/corev1/nodeaffinity.
"""

from __future__ import annotations

from typing import Any

JSON = dict[str, Any]


def match_label_selector(selector: JSON | None, labels: dict[str, str]) -> bool:
    """metav1.LabelSelector match. An empty/None selector matches everything
    (matches metav1.LabelSelectorAsSelector: nil => Nothing is NOT the case
    here — the reference passes a concrete selector struct, where empty
    means Everything)."""
    if not selector:
        return True
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        if not _match_label_expression(expr, labels):
            return False
    return True


def _match_label_expression(expr: JSON, labels: dict[str, str]) -> bool:
    key = expr.get("key", "")
    op = expr.get("operator", "")
    values = expr.get("values") or []
    if op == "In":
        return key in labels and labels[key] in values
    if op == "NotIn":
        # Upstream labels.Requirement.Matches: a NotIn requirement is
        # SATISFIED when the key is absent (selector.go: `if !ls.Has(key)
        # { return true }` for NotIn/NotEquals) — discovered by the
        # independent NodeAffinity operator fixture; presence was wrongly
        # required here before round 3.
        return key not in labels or labels[key] not in values
    if op == "Exists":
        return key in labels
    if op == "DoesNotExist":
        return key not in labels
    raise ValueError(f"unknown label selector operator {op!r}")


def match_node_selector_requirement(req: JSON, labels: dict[str, str]) -> bool:
    """v1.NodeSelectorRequirement on labels: adds Gt/Lt over integer values
    (upstream nodeaffinity.nodeSelectorRequirementsAsSelector)."""
    key = req.get("key", "")
    op = req.get("operator", "")
    values = req.get("values") or []
    if op in ("In", "NotIn", "Exists", "DoesNotExist"):
        return _match_label_expression(
            {"key": key, "operator": op, "values": values}, labels
        )
    if op in ("Gt", "Lt"):
        if key not in labels or len(values) != 1:
            return False
        try:
            lbl = int(labels[key])
            val = int(values[0])
        except ValueError:
            return False
        return lbl > val if op == "Gt" else lbl < val
    raise ValueError(f"unknown node selector operator {op!r}")


def match_node_selector_term(
    term: JSON, node_labels: dict[str, str], node_name: str = ""
) -> bool:
    """One NodeSelectorTerm: AND of matchExpressions (against labels only)
    and matchFields (only metadata.name is supported — upstream
    nodeaffinity.go; a term naming any other field matches nothing).  An
    empty term matches nothing."""
    exprs = term.get("matchExpressions") or []
    fields = term.get("matchFields") or []
    if not exprs and not fields:
        return False
    for req in exprs:
        if not match_node_selector_requirement(req, node_labels):
            return False
    for req in fields:
        if req.get("key") != "metadata.name":
            return False
        if not match_node_selector_requirement(req, {"metadata.name": node_name}):
            return False
    return True


def match_node_selector_terms(
    terms: list[JSON], node_labels: dict[str, str], node_name: str = ""
) -> bool:
    """NodeSelector: OR over terms; empty list matches nothing."""
    return any(match_node_selector_term(t, node_labels, node_name) for t in terms)
