"""Cluster state: typed resources, quantities, snapshot JSON, featurization."""

from ksim_tpu.state.quantity import Quantity, parse_quantity
from ksim_tpu.state.cluster import ClusterStore, WatchEvent

__all__ = ["Quantity", "parse_quantity", "ClusterStore", "WatchEvent"]
