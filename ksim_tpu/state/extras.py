"""Encodings for the lighter default-profile plugins: NodeName, NodePorts,
ImageLocality.

Same host/device split as the other encoders (state/encoding.py): exact
vocabulary construction and matching in Python, fixed-shape int/bool
tensors for the kernels (the reference exercises these plugins through its
wrapped-plugin recording, reference simulator/scheduler/plugin/
wrappedplugin.go:420-548; semantics re-derived from upstream
kube-scheduler v1.30 plugins/{nodename,nodeports,imagelocality}).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ksim_tpu.state.resources import JSON, name_of

# Upstream nodeports: empty hostIP means "bind all".
BIND_ALL_IP = "0.0.0.0"
DEFAULT_PROTOCOL = "TCP"


@dataclass
class NodeNameTensors:
    """pod_req_node: requested node's index, -1 = no request, -2 = the
    requested node is not in the snapshot (always fails)."""

    AXES = {"pod_req_node": "pod"}

    pod_req_node: np.ndarray  # i32 [P]


def encode_node_name(
    nodes: Sequence[JSON], pods: Sequence[JSON], p_padded: int
) -> NodeNameTensors:
    index = {name_of(n): i for i, n in enumerate(nodes)}
    out = np.full(p_padded, -1, dtype=np.int32)
    for j, p in enumerate(pods):
        want = p.get("spec", {}).get("nodeName") or ""
        if want:
            out[j] = index.get(want, -2)
    return NodeNameTensors(pod_req_node=out)


def _host_ports(pod: JSON) -> list[tuple[str, str, int]]:
    """The pod's (hostIP, protocol, hostPort) triples, upstream
    getContainerPorts (hostPort == 0 entries are ignored).  Memoized per
    pod object."""
    from ksim_tpu.state import objcache

    def build() -> list[tuple[str, str, int]]:
        out = []
        for c in pod.get("spec", {}).get("containers") or []:
            for port in c.get("ports") or []:
                hp = int(port.get("hostPort") or 0)
                if hp <= 0:
                    continue
                out.append(
                    (
                        port.get("hostIP") or BIND_ALL_IP,
                        port.get("protocol") or DEFAULT_PROTOCOL,
                        hp,
                    )
                )
        return out

    return objcache.cached("hostports", pod, build)


def ports_conflict(a: tuple[str, str, int], b: tuple[str, str, int]) -> bool:
    """Upstream nodeports Fits / schedutil.PortsConflict semantics."""
    if a[1] != b[1] or a[2] != b[2]:
        return False
    return a[0] == b[0] or a[0] == BIND_ALL_IP or b[0] == BIND_ALL_IP


@dataclass
class NodePortTensors:
    """V = distinct wanted-port triples across queue pods.

    ``conflict_counts`` [N, V] counts existing (bound) pod ports on each
    node conflicting with vocab entry v — the scan carry.  ``pod_wants``
    marks the pod's own triples; ``pod_adds`` counts how many of the
    pod's triples conflict with each vocab entry (the commit delta)."""

    AXES = {
        "conflict_counts": "node",
        "pod_wants": "pod",
        "pod_adds": "pod",
    }

    conflict_counts: np.ndarray  # i32 [N, V]
    pod_wants: np.ndarray  # bool [P, V]
    pod_adds: np.ndarray  # i32 [P, V]


# Trivial no-host-ports tensors per (n_padded, p_padded).
_NO_PORTS: dict = {}


def encode_node_ports(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    bound_pods: Sequence[JSON],
    n_padded: int,
    p_padded: int,
) -> NodePortTensors:
    vocab: dict[tuple[str, str, int], int] = {}
    pod_ports = [_host_ports(p) for p in pods]
    for ports in pod_ports:
        for t in ports:
            vocab.setdefault(t, len(vocab))
    from ksim_tpu.state.featurizer import vocab_pad

    v = vocab_pad(len(vocab))
    if not vocab:
        # No queue pod wants a host port: every tensor is zero whatever
        # the bound pods hold — skip the bound walk (churn steady state).
        hit = _NO_PORTS.get((n_padded, p_padded))
        if hit is None:
            hit = NodePortTensors(
                conflict_counts=np.zeros((n_padded, v), dtype=np.int32),
                pod_wants=np.zeros((p_padded, v), dtype=bool),
                pod_adds=np.zeros((p_padded, v), dtype=np.int32),
            )
            if len(_NO_PORTS) > 64:
                _NO_PORTS.clear()
            _NO_PORTS[(n_padded, p_padded)] = hit
        return hit
    entries = list(vocab)

    conflict_counts = np.zeros((n_padded, v), dtype=np.int32)
    node_index = {name_of(n): i for i, n in enumerate(nodes)}
    for bp in bound_pods:
        ni = node_index.get(bp.get("spec", {}).get("nodeName", ""))
        if ni is None:
            continue
        for t in _host_ports(bp):
            for vi, entry in enumerate(entries):
                if ports_conflict(t, entry):
                    conflict_counts[ni, vi] += 1

    pod_wants = np.zeros((p_padded, v), dtype=bool)
    pod_adds = np.zeros((p_padded, v), dtype=np.int32)
    for j, ports in enumerate(pod_ports):
        for t in ports:
            pod_wants[j, vocab[t]] = True
            for vi, entry in enumerate(entries):
                if ports_conflict(t, entry):
                    pod_adds[j, vi] += 1
    return NodePortTensors(
        conflict_counts=conflict_counts, pod_wants=pod_wants, pod_adds=pod_adds
    )


def normalized_image_name(name: str) -> str:
    """Upstream imagelocality normalizedImageName: append :latest when no
    tag/digest is present."""
    if ":" not in name.rsplit("/", 1)[-1]:
        name = name + ":latest"
    return name


@dataclass
class ImageTensors:
    """I = distinct (normalized) images across queue pods' containers.

    Sizes/spread come from node.status.images summaries; scores follow
    upstream scaledImageScore + calculatePriority."""

    AXES = {
        "node_has_image": "node",
        "image_size": None,
        "image_num_nodes": None,
        "total_nodes_f": None,
        "pod_image_count": "pod",
        "pod_num_containers": "pod",
    }

    total_nodes: int  # real node count (info; device reads total_nodes_f)
    total_nodes_f: np.ndarray  # f64 scalar (traced so churn reuses programs)
    node_has_image: np.ndarray  # bool [N, I]
    image_size: np.ndarray  # f64 [I] bytes (sizeBytes summary)
    image_num_nodes: np.ndarray  # i32 [I] nodes reporting the image
    pod_image_count: np.ndarray  # i32 [P, I] containers using image i
    pod_num_containers: np.ndarray  # i32 [P]


def encode_image_locality(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    n_padded: int,
    p_padded: int,
) -> ImageTensors:
    from ksim_tpu.state import objcache

    def pod_images(p: JSON) -> tuple[int, list[str]]:
        """(container count, normalized image names), memoized per pod."""

        def build() -> tuple[int, list[str]]:
            containers = p.get("spec", {}).get("containers") or []
            return (
                len(containers),
                [normalized_image_name(c["image"]) for c in containers if c.get("image")],
            )

        return objcache.cached("podimgs", p, build)

    vocab: dict[str, int] = {}
    pod_imgs: list[list[int]] = []
    n_containers = np.zeros(p_padded, dtype=np.int32)
    for j, p in enumerate(pods):
        nc, names = pod_images(p)
        n_containers[j] = nc
        pod_imgs.append([vocab.setdefault(nm, len(vocab)) for nm in names])

    from ksim_tpu.state.featurizer import vocab_pad

    i = vocab_pad(len(vocab))

    def build_node_side():
        node_has = np.zeros((n_padded, i), dtype=bool)
        size = np.zeros(i, dtype=np.float64)
        num_nodes = np.zeros(i, dtype=np.int32)
        for ni, node in enumerate(nodes):
            for img in node.get("status", {}).get("images") or []:
                sz = float(img.get("sizeBytes") or 0)
                for nm in img.get("names") or []:
                    vi = vocab.get(normalized_image_name(nm))
                    if vi is not None and not node_has[ni, vi]:
                        node_has[ni, vi] = True
                        num_nodes[vi] += 1
                        size[vi] = max(size[vi], sz)
        return node_has, size, num_nodes

    # Family-cached on (exact node objects, image vocab): identical
    # whenever neither changed — every churn pass without a node event
    # once the image vocabulary stabilizes.
    node_has, size, num_nodes = objcache.cached_seq(
        "enc_img_nodes", nodes, build_node_side, tuple(vocab), n_padded
    )

    pod_image_count = np.zeros((p_padded, i), dtype=np.int32)
    for j, imgs in enumerate(pod_imgs):
        for vi in imgs:
            pod_image_count[j, vi] += 1
    return ImageTensors(
        total_nodes=max(len(nodes), 1),
        total_nodes_f=np.asarray(float(max(len(nodes), 1))),
        node_has_image=node_has,
        image_size=size,
        image_num_nodes=num_nodes,
        pod_image_count=pod_image_count,
        pod_num_containers=n_containers,
    )
