"""Snapshot export/import, JSON-compatible with the reference.

The export shape mirrors ``ResourcesForSnap`` exactly (reference
simulator/snapshot/snapshot.go:33-42): keys ``pods, nodes, pvs, pvcs,
storageClasses, priorityClasses, schedulerConfig, namespaces`` — so a file
exported from the reference simulator loads here and vice versa.

Behavioral parity points:
- label-selector filtered export (snapshot.go:104-140);
- system priority classes (name prefixed ``system-``) are excluded on both
  snap and load (snapshot.go:586-591 isSystemPriorityClass);
- ``kube-``-prefixed namespaces are excluded (snapshot.go:593-599);
- load applies in dependency order: namespaces first, then priority
  classes / storage classes / pvcs / nodes / pods, PVs last so a PV's
  claimRef UID can be re-resolved to the freshly-created PVC
  (snapshot.go:158-196 and the fixClaimRef logic in utils.go);
- IgnoreErr mode logs-and-continues per object (snapshot.go:90-94).
"""

from __future__ import annotations

import json
import logging
from typing import Any

from ksim_tpu.errors import SimulatorError
from ksim_tpu.state.cluster import ClusterStore
from ksim_tpu.state.resources import JSON, labels_of, name_of
from ksim_tpu.state.selectors import match_label_selector

logger = logging.getLogger(__name__)

# snapshot-JSON key -> cluster-store kind
_FIELD_KINDS = (
    ("pods", "pods"),
    ("nodes", "nodes"),
    ("pvs", "persistentvolumes"),
    ("pvcs", "persistentvolumeclaims"),
    ("storageClasses", "storageclasses"),
    ("priorityClasses", "priorityclasses"),
    ("namespaces", "namespaces"),
)

# Dependency order for load (reference snapshot.go:158-196).
_LOAD_ORDER = (
    ("namespaces", "namespaces"),
    ("priorityClasses", "priorityclasses"),
    ("storageClasses", "storageclasses"),
    ("pvcs", "persistentvolumeclaims"),
    ("nodes", "nodes"),
    ("pods", "pods"),
    ("pvs", "persistentvolumes"),
)


def is_system_priority_class(name: str) -> bool:
    return name.startswith("system-")


def is_ignored_namespace(name: str) -> bool:
    return name.startswith("kube-")


class SnapshotService:
    """Snap/Load against a ClusterStore (reference snapshot.Service)."""

    def __init__(self, store: ClusterStore, scheduler_service: Any = None) -> None:
        self._store = store
        self._scheduler_service = scheduler_service

    def snap(self, label_selector: JSON | None = None) -> JSON:
        out: JSON = {}
        for field, kind in _FIELD_KINDS:
            objs = self._store.list(kind)
            if label_selector:
                objs = [o for o in objs if match_label_selector(label_selector, labels_of(o))]
            if field == "priorityClasses":
                objs = [o for o in objs if not is_system_priority_class(name_of(o))]
            if field == "namespaces":
                objs = [o for o in objs if not is_ignored_namespace(name_of(o))]
            out[field] = objs
        cfg = None
        if self._scheduler_service is not None:
            cfg = self._scheduler_service.get_scheduler_config()
        out["schedulerConfig"] = cfg
        return out

    def load(
        self,
        resources: JSON,
        *,
        ignore_err: bool = False,
        ignore_scheduler_configuration: bool = False,
    ) -> None:
        for field, kind in _LOAD_ORDER:
            for obj in resources.get(field) or []:
                if field == "priorityClasses" and is_system_priority_class(name_of(obj)):
                    continue
                if field == "namespaces" and is_ignored_namespace(name_of(obj)):
                    continue
                try:
                    obj = dict(obj)
                    md = dict(obj.get("metadata") or {})
                    # Apply semantics: never carry a foreign UID in
                    # (snapshot.go applyPcs: pc.UID = nil).
                    md.pop("uid", None)
                    md.pop("resourceVersion", None)
                    obj["metadata"] = md
                    if field == "pvs":
                        obj = self._fix_claim_ref(obj)
                    self._store.apply(kind, obj)
                except SimulatorError:
                    if not ignore_err:
                        raise
                    logger.error("failed to apply %s %s", kind, name_of(obj))
        cfg = resources.get("schedulerConfig")
        if (
            cfg is not None
            and not ignore_scheduler_configuration
            and self._scheduler_service is not None
        ):
            # apply_scheduler_config is the restart analogue: compile-and-
            # swap with rollback (reference snapshot.go:202-219 calls
            # RestartScheduler after load).
            self._scheduler_service.apply_scheduler_config(cfg)

    def _fix_claim_ref(self, pv: JSON) -> JSON:
        """Re-resolve a Bound PV's claimRef UID to the freshly-loaded PVC —
        the reason PVs load last (reference snapshot.go applyPvs:
        source-cluster UIDs are meaningless here).  Matches the reference:
        only PVs with status.phase == Bound are touched, and a missing PVC
        clears the UID rather than keeping the stale one."""
        if (pv.get("status") or {}).get("phase") != "Bound":
            return pv
        ref = (pv.get("spec") or {}).get("claimRef")
        if not ref or not ref.get("name"):
            return pv
        try:
            pvc = self._store.get(
                "persistentvolumeclaims", ref["name"], ref.get("namespace", "default")
            )
            uid = pvc["metadata"].get("uid")
        except SimulatorError:
            uid = None
        pv = dict(pv)
        spec = dict(pv.get("spec") or {})
        spec["claimRef"] = {**ref, "uid": uid}
        pv["spec"] = spec
        return pv

    # -- file helpers -------------------------------------------------------

    def export_json(self, label_selector: JSON | None = None) -> str:
        return json.dumps(self.snap(label_selector), separators=(",", ":"))

    def import_json(self, data: str | bytes, **kwargs: Any) -> None:
        self.load(json.loads(data), **kwargs)
