"""Pod priority resolution through PriorityClass objects.

Upstream admission writes ``spec.priority`` from the pod's
``priorityClassName`` before the scheduler ever sees the pod; snapshots
taken from live clusters carry the resolved value, but hand-written or
KWOK-originated pods may only name the class.  The resolver mirrors the
admission plugin: explicit ``spec.priority`` wins, then the named class's
value, then the globalDefault class, then 0.  The built-in system classes
exist even when the snapshot omits them (upstream
scheduling.SystemCriticalPriority)."""

from __future__ import annotations

from typing import Callable, Sequence

from ksim_tpu.state.resources import JSON, name_of

SYSTEM_PRIORITY_CLASSES = {
    "system-cluster-critical": 2_000_000_000,
    "system-node-critical": 2_000_001_000,
}


def build_priority_resolver(
    priority_classes: Sequence[JSON] = (),
) -> Callable[[JSON], int]:
    by_name = dict(SYSTEM_PRIORITY_CLASSES)
    default = 0
    for pc in priority_classes:
        by_name[name_of(pc)] = int(pc.get("value") or 0)
        if pc.get("globalDefault"):
            default = int(pc.get("value") or 0)

    def resolve(pod: JSON) -> int:
        spec = pod.get("spec", {})
        if spec.get("priority") is not None:
            return int(spec["priority"])
        class_name = spec.get("priorityClassName")
        if class_name:
            return by_name.get(class_name, 0)
        return default

    return resolve
