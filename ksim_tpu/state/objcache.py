"""Per-object parse memos for the host featurization path.

Churn replay featurizes the whole cluster every scheduling pass, but most
objects are unchanged between passes: the cluster store hands out the
SAME dict object for an unchanged resource (``list(copy_objs=False)``)
and a brand-new dict on every write (create/update/patch all deepcopy
before storing, state/cluster.py).  ``id(obj)`` therefore identifies a
frozen snapshot of an object's content for as long as that object is
alive — and the memo keeps a strong reference to every key object so its
id cannot be recycled while an entry exists.

Sub-objects inherit the property: a pod's ``spec.affinity`` term dicts
are replaced together with the pod, so they are valid memo keys too.

Callers that build JSON by hand (tests, library use) must not mutate an
object in place after featurizing it — mutate-and-refeaturize would see
stale parses.  The store path never does this.  ``clear()`` drops
everything (used by tests and when the table hits its size limit).
"""

from __future__ import annotations

from typing import Any, Callable

_MISS = object()

_DATA: dict[Any, Any] = {}
_REFS: dict[int, Any] = {}

# Entry limit: a 50k-event churn creates ~100k pod objects with a handful
# of memo slots each; one mid-run clear is cheaper than unbounded growth.
LIMIT = 1 << 19


def ref_id(obj: Any) -> int:
    """id(obj), pinned: the object stays alive while the memo does."""
    i = id(obj)
    if i not in _REFS:
        _REFS[i] = obj
    return i


def get(key: Any) -> Any:
    """Lookup; returns the module sentinel ``MISS`` when absent."""
    return _DATA.get(key, _MISS)


MISS = _MISS


def put(key: Any, value: Any) -> Any:
    """Store an entry.  Never clears inline: a clear here would unpin the
    in-flight key object (its id was taken by the caller before the
    clear), letting the id be recycled under a surviving entry.  Size
    enforcement happens at safe points via maybe_flush()."""
    _DATA[key] = value
    return value


def maybe_flush() -> None:
    """Clear the table if it exceeds LIMIT.  Called at points where no
    memo key is in flight (the featurizer's entry) so every surviving
    entry's key object gets re-pinned by ref_id before reuse."""
    if len(_DATA) >= LIMIT:
        clear()


def cached(slot: str, obj: Any, fn: Callable[[], Any], *extra: Any) -> Any:
    """Memoize ``fn()`` under (slot, id(obj), *extra)."""
    key = (slot, ref_id(obj), *extra)
    hit = _DATA.get(key, _MISS)
    if hit is not _MISS:
        return hit
    return put(key, fn())


def clear() -> None:
    _DATA.clear()
    _REFS.clear()


def stats() -> dict[str, int]:
    return {"entries": len(_DATA), "refs": len(_REFS)}
