"""Per-object parse memos for the host featurization path.

Churn replay featurizes the whole cluster every scheduling pass, but most
objects are unchanged between passes: the cluster store hands out the
SAME dict object for an unchanged resource (``list(copy_objs=False)``)
and a brand-new dict on every write (create/update/patch all deepcopy
before storing, state/cluster.py).  ``id(obj)`` therefore identifies a
frozen snapshot of an object's content for as long as that object is
alive — and the memo keeps a strong reference to every key object so its
id cannot be recycled while an entry exists.

Sub-objects inherit the property: a pod's ``spec.affinity`` term dicts
are replaced together with the pod, so they are valid memo keys too.

Eviction is generational, not clear-all: entries touched recently
survive, entries untouched for a few generations are swept and their key
objects unpinned (a clear-all would force a cold re-parse of the whole
working set at once).  Note that with the incremental bound-pod
aggregation (state/boundagg.py) an unchanged bound pod's parse entries
may legitimately go untouched for many passes — its contribution lives
in the aggregate's records instead — so a sweep can evict entries for
still-live pods; the cost surfaces only as a one-pass cold re-parse on
the next full rebuild (vocabulary growth or unit rescale), which is the
same cost the rebuild itself already carries.  By convention ``key[1]``
is the pinned object's id (see ``ref_id``), which is how the sweep knows
which pins survive.

Callers that build JSON by hand (tests, library use) must not mutate an
object in place after featurizing it — mutate-and-refeaturize would see
stale parses.  The store path never does this.  ``clear()`` drops
everything.
"""

from __future__ import annotations

import collections
from typing import Any, Callable

_MISS = object()

# key -> [value, last_access_generation]; key[1] is the pinned id.
_DATA: dict[Any, list] = {}
_REFS: dict[int, Any] = {}
_GEN = 0

# Sweep trigger: ~10 slots per live pod means 512k entries ≈ 50k live
# objects — far above any benchmarked cluster, so sweeps are rare.  The
# working limit doubles whenever a sweep can't reclaim half the table
# (see maybe_flush); LIMIT is the starting point.
LIMIT = 1 << 19
_limit: "int | None" = None  # set past LIMIT when sweeps can't reclaim
# Entries untouched for this many generations are considered dead.  Live
# objects are touched every featurization; 4 covers multi-profile setups
# where alternating profiles featurize disjoint queues.
STALE_GENERATIONS = 4


def ref_id(obj: Any) -> int:
    """id(obj), pinned: the object stays alive while the memo does."""
    i = id(obj)
    if i not in _REFS:
        _REFS[i] = obj
    return i


def get(key: Any) -> Any:
    """Lookup; returns the module sentinel ``MISS`` when absent."""
    entry = _DATA.get(key)
    if entry is None:
        return _MISS
    entry[1] = _GEN
    return entry[0]


MISS = _MISS


def put(key: Any, value: Any) -> Any:
    """Store an entry.  Never evicts inline: an eviction here could unpin
    the in-flight key object (its id was taken by the caller before the
    sweep), letting the id be recycled under a surviving entry.  Size
    enforcement happens at safe points via maybe_flush()."""
    _DATA[key] = [value, _GEN]
    return value


def maybe_flush() -> None:
    """Advance the generation; sweep stale entries when over the limit.

    Called at points where no memo key is in flight (the featurizer's
    entry), so surviving entries' key objects stay pinned and swept ids
    are only unpinned when no entry references them.

    If a sweep frees little (the working set is genuinely that large),
    the limit doubles so the O(table) sweep scan stays amortized instead
    of running — and evicting nothing — on every subsequent pass."""
    global _GEN, _limit
    _GEN += 1
    limit = _limit if _limit is not None else LIMIT
    if len(_DATA) < limit:
        return
    floor = _GEN - STALE_GENERATIONS
    for key in [k for k, e in _DATA.items() if e[1] < floor]:
        del _DATA[key]
    live_ids = {k[1] for k in _DATA}
    for i in [i for i in _REFS if i not in live_ids]:
        del _REFS[i]
    if len(_DATA) > limit // 2:
        _limit = limit * 2
    elif _limit is not None and len(_DATA) < LIMIT // 2:
        _limit = None  # working set shrank back; restore the baseline


def cached(slot: str, obj: Any, fn: Callable[[], Any], *extra: Any) -> Any:
    """Memoize ``fn()`` under (slot, id(obj), *extra)."""
    key = (slot, ref_id(obj), *extra)
    hit = get(key)
    if hit is not _MISS:
        return hit
    return put(key, fn())


# Family-cache table, SEPARATE from _DATA: entries hold multi-MB arrays
# and pin a whole node list each, so the per-object memo's ~512k-entry
# sweep threshold would never trigger — a bounded LRU of a few dozen is
# the right shape (7 families x a handful of live token/node-list
# variants; anything older is dead after the next node event anyway).
_SEQ: "collections.OrderedDict[Any, Any]" = collections.OrderedDict()
_SEQ_LIMIT = 64


def cached_seq(slot: str, objs: Any, fn: Callable[[], Any], *extra: Any) -> Any:
    """Memoize ``fn()`` under (slot, tuple-of-ids(objs), *extra) — the
    family form of ``cached`` for whole-sequence builds (an encoder's
    node-side tables: identical whenever the exact same node objects and
    vocabulary token recur, which under churn is every pass without a
    node event).

    Unlike ``cached``, the entry pins its key objects ITSELF: the stored
    value carries strong references to every object in ``objs``, so none
    of their ids can be recycled while the entry lives.  (The ``key[1]``
    pin convention doesn't extend to id-tuples — a sweep would unpin
    the members and a recycled id could alias a different object into a
    stale hit.)  Eviction is LRU over a small dedicated table."""
    seq = tuple(objs)
    key = (slot, tuple(map(id, seq)), *extra)
    hit = _SEQ.get(key)
    if hit is not None:
        _SEQ.move_to_end(key)
        return hit[0]
    value = fn()
    _SEQ[key] = (value, seq)
    if len(_SEQ) > _SEQ_LIMIT:
        _SEQ.popitem(last=False)
    return value


# Token interning: per-pod memo keys embed vocabulary tokens (tuples of
# canonical strings, often hundreds of entries).  Hashing such a tuple
# on EVERY lookup is O(vocab) per pod per family; interning maps it to a
# small int once per pass so the per-pod keys hash in O(1).
_INTERN: dict[Any, int] = {}
_INTERN_NEXT = 0


def intern_token(token: Any) -> int:
    """Small stable int for a hashable token (hashed once, here).

    Reset valve: if an adversarial stream mints unbounded distinct
    tokens, the WHOLE memo resets with the intern table.  Ints come from
    a MONOTONIC counter (never restarted): callers capture interned ints
    in locals and may write memo entries with them after the valve
    fires, so a restarted numbering could hand a later token an int an
    in-flight key still embeds — aliasing a fresh lookup into a stale
    entry."""
    global _INTERN_NEXT
    i = _INTERN.get(token)
    if i is None:
        if len(_INTERN) > (1 << 16):
            _DATA.clear()
            _REFS.clear()
            _INTERN.clear()
        i = _INTERN_NEXT
        _INTERN_NEXT += 1
        _INTERN[token] = i
    return i


def clear() -> None:
    global _GEN, _limit
    _DATA.clear()
    _REFS.clear()
    _INTERN.clear()
    _SEQ.clear()
    _GEN = 0
    _limit = None


def stats() -> dict[str, int]:
    return {
        "entries": len(_DATA),
        "refs": len(_REFS),
        "generation": _GEN,
        "seq_entries": len(_SEQ),
        "interned": len(_INTERN),
    }
