"""Vocabulary encodings: labels/selector terms and taints as tensors.

SURVEY.md hard part 5 — "expressing label/taint/affinity matching as
tensors".  The split that keeps semantics exact AND the device path dense:

- **Host side** (here, numpy + exact string matching): build vocabularies
  of distinct selector *requirements* (key, operator, values) and *terms*
  (conjunctions of requirements) across the pod set, evaluate every
  requirement against every node's labels once (Q x N boolean matrix),
  and evaluate each pod's tolerations against the cluster's distinct
  taints (P x W boolean matrix).  All In/NotIn/Exists/DoesNotExist/Gt/Lt
  and toleration operator semantics run in Python — bit-exact by
  construction (state/selectors.py, state/resources.py).
- **Device side** (plugins/nodeaffinity.py, plugins/tainttoleration.py):
  term matching reduces to an integer matmul — a node matches term t iff
  its satisfied-requirement count over the term's requirement set equals
  the term size — and taint filtering/scoring to masked reductions.

Everything here keys into ``FeaturizedSnapshot.aux`` and rides into the
jitted programs as traced inputs (never baked constants).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ksim_tpu.state.resources import (
    JSON,
    labels_of,
    name_of,
    pod_tolerations,
    toleration_tolerates,
)
from ksim_tpu.state.selectors import match_node_selector_requirement

FORBIDDING_EFFECTS = ("NoSchedule", "NoExecute")


# -- node-affinity / node-selector encoding ---------------------------------


def _vpad(n: int, minimum: int = 8) -> int:
    from ksim_tpu.state.featurizer import vocab_pad

    return vocab_pad(n, minimum)


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass
class AffinityTensors:
    """Term-algebra arrays for NodeAffinity + pod.spec.nodeSelector."""

    # Leading-axis kind per field, consumed by engine/sharding.shard_aux
    # ("node" -> tp, "pod" -> dp, None -> replicated).
    AXES = {
        "node_req_match": "node",
        "term_req": None,
        "term_size": None,
        "selector_term": "pod",
        "has_required": "pod",
        "required_terms": "pod",
        "preferred_weights": "pod",
        "added_terms": None,
        "has_added": None,
        "added_pref": None,
    }

    node_req_match: np.ndarray  # bool [N(padded), Q]
    term_req: np.ndarray  # bool [T, Q]
    term_size: np.ndarray  # int32 [T] (-1 for empty terms: match nothing)
    selector_term: np.ndarray  # int32 [P(padded)] index into T, -1 = none
    has_required: np.ndarray  # bool [P]
    required_terms: np.ndarray  # bool [P, T]
    preferred_weights: np.ndarray  # int32 [P, T]
    # NodeAffinityArgs.addedAffinity (profile-level, upstream
    # node_affinity.go addedNodeSelector/addedPrefSchedTerms): required
    # terms ANDed into every pod's filter, preferred weights added to
    # every pod's score.
    added_terms: np.ndarray  # bool [T]
    has_added: np.ndarray  # bool [1]
    added_pref: np.ndarray  # int32 [T]

    @property
    def n_terms(self) -> int:
        return self.term_req.shape[0]


class _TermVocab:
    def __init__(self) -> None:
        self.reqs: dict[str, int] = {}
        self.req_list: list[JSON] = []
        self.terms: dict[str, int] = {}
        self.term_list: list[list[int]] = []

    def req_id(self, req: JSON) -> int:
        return self.req_id_by_key(_canon(req), req)

    def req_id_by_key(self, k: str, req: JSON) -> int:
        if k not in self.reqs:
            self.reqs[k] = len(self.req_list)
            self.req_list.append(req)
        return self.reqs[k]

    def term_id(self, reqs: Sequence[JSON]) -> int:
        return self._term_of_ids(sorted(self.req_id(r) for r in reqs))

    def term_id_by_keys(self, pairs: Sequence[tuple[JSON, str]]) -> int:
        """Term id from (req, canonical-key) pairs — skips re-canoning."""
        return self._term_of_ids(sorted(self.req_id_by_key(k, r) for r, k in pairs))

    def _term_of_ids(self, ids: list[int]) -> int:
        k = _canon(ids)
        if k not in self.terms:
            self.terms[k] = len(self.term_list)
            self.term_list.append(ids)
        return self.terms[k]


def _term_reqs_from_selector_term(term: JSON) -> list[JSON] | None:
    """NodeSelectorTerm -> requirement list; None for terms that match
    nothing: the empty term, or a matchFields key other than metadata.name
    (the only supported field — upstream nodeaffinity.go)."""
    reqs = []
    for e in term.get("matchExpressions") or []:
        reqs.append(dict(e))
    for f in term.get("matchFields") or []:
        if f.get("key") != "metadata.name":
            return None
        reqs.append({**f, "_field": True})
    return reqs or None


def _parsed_node_affinity(pod: JSON) -> dict:
    """Vocab-independent nodeSelector/nodeAffinity parse with canonical
    requirement keys, memoized per pod object.  Pairs are (req, canon)."""
    from ksim_tpu.state import objcache

    def build() -> dict:
        spec = pod.get("spec", {})
        out: dict = {"sel": None, "req": None, "pref": []}
        ns = spec.get("nodeSelector")
        if ns:
            reqs = [
                {"key": k, "operator": "In", "values": [v]} for k, v in sorted(ns.items())
            ]
            out["sel"] = [(r, _canon(r)) for r in reqs]
        aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
        required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
        if required is not None:
            terms = []
            for t in required.get("nodeSelectorTerms") or []:
                reqs = _term_reqs_from_selector_term(t)
                terms.append(None if reqs is None else [(r, _canon(r)) for r in reqs])
            out["req"] = terms
        for pt in aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            reqs = _term_reqs_from_selector_term(pt.get("preference") or {})
            out["pref"].append(
                (
                    None if reqs is None else [(r, _canon(r)) for r in reqs],
                    int(pt.get("weight", 0)),
                )
            )
        return out

    return objcache.cached("affpod", pod, build)


def encode_affinity(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    n_padded: int,
    p_padded: int,
    added_affinity: JSON | None = None,
) -> AffinityTensors:
    from ksim_tpu.state import objcache

    vocab = _TermVocab()

    def term_of_pairs(pairs: list[tuple[JSON, str]]) -> int:
        return vocab.term_id_by_keys(pairs)

    sel_term = np.full(p_padded, -1, dtype=np.int32)
    has_req = np.zeros(p_padded, dtype=bool)
    req_terms: list[list[int]] = [[] for _ in range(p_padded)]
    pref: list[dict[int, int]] = [{} for _ in range(p_padded)]

    # Profile-level addedAffinity terms register in the same vocabulary
    # (upstream NodeAffinityArgs.addedAffinity, node_affinity.go New).
    added_req_ids: list[int] = []
    has_added = False
    added_pref_ids: dict[int, int] = {}
    if added_affinity:
        required = added_affinity.get("requiredDuringSchedulingIgnoredDuringExecution")
        if required is not None:
            has_added = True
            for t in required.get("nodeSelectorTerms") or []:
                reqs = _term_reqs_from_selector_term(t)
                if reqs is not None:
                    added_req_ids.append(vocab.term_id(reqs))
        for pt in added_affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            reqs = _term_reqs_from_selector_term(pt.get("preference") or {})
            if reqs is not None:
                tid = vocab.term_id(reqs)
                added_pref_ids[tid] = added_pref_ids.get(tid, 0) + int(pt.get("weight", 0))

    for j, pod in enumerate(pods):
        parsed = _parsed_node_affinity(pod)
        if parsed["sel"] is not None:
            sel_term[j] = term_of_pairs(parsed["sel"])
        if parsed["req"] is not None:
            has_req[j] = True
            for pairs in parsed["req"]:
                # Match-nothing terms contribute nothing to the OR.
                if pairs is not None:
                    req_terms[j].append(term_of_pairs(pairs))
        for pairs, w in parsed["pref"]:
            if pairs is not None:
                tid = term_of_pairs(pairs)
                pref[j][tid] = pref[j].get(tid, 0) + w

    Q = _vpad(len(vocab.req_list))
    T = _vpad(len(vocab.term_list))
    Q0 = len(vocab.req_list)
    reqs_token = tuple(vocab.reqs)
    reqs_tok = objcache.intern_token(reqs_token)

    def node_row(node: JSON) -> np.ndarray:
        key = ("affnode", objcache.ref_id(node), reqs_tok)
        hit = objcache.get(key)
        if hit is not objcache.MISS:
            return hit
        lbls = dict(labels_of(node))
        field_lbls = {"metadata.name": name_of(node)}
        row = np.zeros(Q0, dtype=bool)
        for qi, req in enumerate(vocab.req_list):
            if req.get("_field"):
                r = {k: v for k, v in req.items() if k != "_field"}
                row[qi] = match_node_selector_requirement(r, field_lbls)
            else:
                row[qi] = match_node_selector_requirement(req, lbls)
        return objcache.put(key, row)

    def build_node_matrix() -> np.ndarray:
        m = np.zeros((n_padded, max(Q, 1)), dtype=bool)
        for ni, node in enumerate(nodes):
            m[ni, :Q0] = node_row(node)
        return m

    # Family-cached on (exact node objects, requirement vocab): the
    # assembled matrix is identical whenever neither changed — every
    # churn pass without a node event once the term vocab stabilizes.
    node_req_match = objcache.cached_seq(
        "enc_aff_nodes", nodes, build_node_matrix, reqs_tok, n_padded
    )

    term_req = np.zeros((max(T, 1), max(Q, 1)), dtype=bool)
    term_size = np.full(max(T, 1), -1, dtype=np.int32)
    for ti, ids in enumerate(vocab.term_list):
        for qi in ids:
            term_req[ti, qi] = True
        term_size[ti] = len(ids)

    required_terms = np.zeros((p_padded, max(T, 1)), dtype=bool)
    preferred_weights = np.zeros((p_padded, max(T, 1)), dtype=np.int32)
    for j in range(p_padded):
        for tid in req_terms[j]:
            required_terms[j, tid] = True
        for tid, w in pref[j].items():
            preferred_weights[j, tid] = w

    added_terms = np.zeros(max(T, 1), dtype=bool)
    for tid in added_req_ids:
        added_terms[tid] = True
    added_pref = np.zeros(max(T, 1), dtype=np.int32)
    for tid, w in added_pref_ids.items():
        added_pref[tid] = w

    return AffinityTensors(
        node_req_match=node_req_match,
        term_req=term_req,
        term_size=term_size,
        selector_term=sel_term,
        has_required=has_req,
        required_terms=required_terms,
        preferred_weights=preferred_weights,
        added_terms=added_terms,
        has_added=np.array([has_added]),
        added_pref=added_pref,
    )


# -- taint / toleration encoding --------------------------------------------


@dataclass
class TaintTensors:
    """Distinct-taint vocabulary arrays."""

    AXES = {
        "node_taint_order": "node",
        "forbidding": None,
        "prefer": None,
        "pod_tolerated": "pod",
        "pod_tolerated_prefer": "pod",
    }

    taints: list[JSON]  # W distinct taints (key, value, effect)
    node_taint_order: np.ndarray  # int32 [N(padded), W], position+1, 0=absent
    forbidding: np.ndarray  # bool [W] effect in (NoSchedule, NoExecute)
    prefer: np.ndarray  # bool [W] effect == PreferNoSchedule
    pod_tolerated: np.ndarray  # bool [P(padded), W] (all tolerations)
    pod_tolerated_prefer: np.ndarray  # bool [P, W] (effect ""|PreferNoSchedule tolerations only)

    @property
    def n_taints(self) -> int:
        return len(self.taints)


def encode_taints(
    nodes: Sequence[JSON], pods: Sequence[JSON], n_padded: int, p_padded: int
) -> TaintTensors:
    from ksim_tpu.state import objcache

    def build_node_side():
        """The taint vocabulary and every node-derived array — a pure
        function of the node list (+ n_padded), cached as a family on
        the exact node objects (objcache.cached_seq): under churn the
        node list is identical most passes, and this loop over every
        node was a top featurize cost."""
        vocab: dict[str, int] = {}
        taints: list[JSON] = []

        def tid(key: str, t: JSON) -> int:
            if key not in vocab:
                vocab[key] = len(taints)
                taints.append(
                    {"key": t.get("key", ""), "value": t.get("value", ""), "effect": t.get("effect", "")}
                )
            return vocab[key]

        def node_taints(node: JSON) -> list[tuple[str, JSON]]:
            """[(canonical key, taint)] per node, memoized per object."""

            def build() -> list[tuple[str, JSON]]:
                return [
                    (
                        _canon({"key": t.get("key", ""), "value": t.get("value", ""), "effect": t.get("effect", "")}),
                        t,
                    )
                    for t in node.get("spec", {}).get("taints") or []
                ]

            return objcache.cached("nodetaints", node, build)

        per_node: list[list[int]] = []
        for node in nodes:
            per_node.append([tid(k, t) for k, t in node_taints(node)])

        W = _vpad(len(taints))
        order = np.zeros((n_padded, W), dtype=np.int32)
        for ni, ids in enumerate(per_node):
            for pos, w in enumerate(ids):
                if order[ni, w] == 0:
                    order[ni, w] = pos + 1
        forbidding = np.zeros(W, dtype=bool)
        prefer = np.zeros(W, dtype=bool)
        for w, t in enumerate(taints):
            forbidding[w] = t["effect"] in FORBIDDING_EFFECTS
            prefer[w] = t["effect"] == "PreferNoSchedule"
        return taints, order, forbidding, prefer, tuple(vocab), W

    taints, order, forbidding, prefer, taints_token, W = objcache.cached_seq(
        "enc_taints_nodes", nodes, build_node_side, n_padded
    )
    W0 = len(taints)
    taints_tok = objcache.intern_token(taints_token)

    def tol_rows(pod: JSON) -> tuple[np.ndarray, np.ndarray]:
        """(tolerated, tolerated_prefer) rows over the taint vocab,
        memoized per (pod object, vocab)."""
        key = ("taintrow", objcache.ref_id(pod), taints_tok)
        hit = objcache.get(key)
        if hit is not objcache.MISS:
            return hit
        tols = pod_tolerations(pod)
        prefer_tols = [t for t in tols if (t.get("effect") or "") in ("", "PreferNoSchedule")]
        row = np.fromiter(
            (any(toleration_tolerates(tl, t) for tl in tols) for t in taints),
            dtype=bool,
            count=W0,
        )
        prow = np.fromiter(
            (any(toleration_tolerates(tl, t) for tl in prefer_tols) for t in taints),
            dtype=bool,
            count=W0,
        )
        return objcache.put(key, (row, prow))

    tolerated = np.zeros((p_padded, W), dtype=bool)
    tolerated_prefer = np.zeros((p_padded, W), dtype=bool)
    for j, pod in enumerate(pods):
        row, prow = tol_rows(pod)
        tolerated[j, :W0] = row
        tolerated_prefer[j, :W0] = prow

    return TaintTensors(
        taints=taints,
        node_taint_order=order,
        forbidding=forbidding,
        prefer=prefer,
        pod_tolerated=tolerated,
        pod_tolerated_prefer=tolerated_prefer,
    )


# -- pod-topology-spread encoding -------------------------------------------


@dataclass
class SpreadTensors:
    """PodTopologySpread constraint tables and per-node selector counts.

    S = distinct selector contexts (namespace, effective labelSelector —
    matchLabelKeys merged in); TK = distinct topology keys; Dom = distinct
    (key, value) domains; MC = max constraints per pod.
    """

    AXES = {
        "node_dom": "node",
        "node_ldom": "node",
        "init_counts": "node",
        "pod_sel_match": "pod",
        "con_valid": "pod",
        "con_mode": "pod",
        "con_sel": "pod",
        "con_tk": "pod",
        "con_max_skew": "pod",
        "con_min_domains": "pod",
        "con_self": "pod",
        "con_honor_aff": "pod",
        "con_honor_taints": "pod",
        "has_score_con": "pod",
    }

    n_domains: int  # static Dom size (for segment ops)
    tk_sizes: tuple  # static per-key local-domain counts (>=1 each)
    tk_singleton: tuple  # static per-key: every domain holds <=1 node
    node_dom: np.ndarray  # int32 [N, TK], domain id or -1
    node_ldom: np.ndarray  # int32 [N, TK], per-key LOCAL domain id or -1
    init_counts: np.ndarray  # int32 [N, S] matching bound pods per node
    pod_sel_match: np.ndarray  # bool [P, S] queue pod matches context
    con_valid: np.ndarray  # bool [P, MC]
    con_mode: np.ndarray  # int32 [P, MC] 0=DoNotSchedule 1=ScheduleAnyway
    con_sel: np.ndarray  # int32 [P, MC] selector-context id
    con_tk: np.ndarray  # int32 [P, MC] topology-key id
    con_max_skew: np.ndarray  # int32 [P, MC]
    con_min_domains: np.ndarray  # int32 [P, MC] 0 = unset
    con_self: np.ndarray  # bool [P, MC] pod matches own selector
    con_honor_aff: np.ndarray  # bool [P, MC] nodeAffinityPolicy Honor
    con_honor_taints: np.ndarray  # bool [P, MC] nodeTaintsPolicy Honor
    has_score_con: np.ndarray  # bool [P]


# Upstream pkg/scheduler/apis/config/v1/defaults.go systemDefaultConstraints
# (defaultingType: System — the reference's exported default config carries
# it, simulator/snapshot/snapshot_test.go:1415).
SYSTEM_DEFAULT_CONSTRAINTS: tuple = (
    {
        "topologyKey": "topology.kubernetes.io/zone",
        "whenUnsatisfiable": "ScheduleAnyway",
        "maxSkew": 3,
    },
    {
        "topologyKey": "kubernetes.io/hostname",
        "whenUnsatisfiable": "ScheduleAnyway",
        "maxSkew": 5,
    },
)


def default_spread_selector(
    pod: JSON,
    services: Sequence[JSON] = (),
    replication_controllers: Sequence[JSON] = (),
    replica_sets: Sequence[JSON] = (),
    stateful_sets: Sequence[JSON] = (),
) -> JSON | None:
    """Upstream helper.DefaultSelector (plugins/helper/spread.go): merge
    the selectors of the services selecting the pod and the pod's
    controller (RC/RS/StatefulSet).  Returns None when the merged
    selector is EMPTY — buildDefaultConstraints then applies NO default
    constraints (pod_topology_spread/common.go ``if selector.Empty()``).

    The snapshot model carries none of these kinds (reference
    simulator/snapshot/snapshot.go:33-42 — pods, nodes, pvs, pvcs,
    storageClasses, priorityClasses, schedulerConfig), so in both the
    reference and here the selector is always empty and
    defaultConstraints/System defaulting are inert: the same blind spot,
    by construction.  The parameters exist so the behavior stays
    upstream-shaped if the snapshot model ever grows these kinds."""
    from ksim_tpu.state.resources import namespace_of

    ns = namespace_of(pod) or "default"
    pod_labels = dict(labels_of(pod))
    merged: dict[str, str] = {}
    for svc in services:
        if (namespace_of(svc) or "default") != ns:
            continue
        sel = (svc.get("spec") or {}).get("selector") or {}
        if sel and all(pod_labels.get(k) == v for k, v in sel.items()):
            merged.update(sel)
    exprs: list[JSON] = []
    owner = next(
        (
            o
            for o in (pod.get("metadata", {}).get("ownerReferences") or [])
            if o.get("controller")
        ),
        None,
    )
    if owner:
        kind = owner.get("kind")
        o_name = owner.get("name")
        pool = {
            "ReplicationController": replication_controllers,
            "ReplicaSet": replica_sets,
            "StatefulSet": stateful_sets,
        }.get(kind, ())
        for obj in pool:
            if name_of(obj) != o_name or (namespace_of(obj) or "default") != ns:
                continue
            sel = (obj.get("spec") or {}).get("selector") or {}
            if kind == "ReplicationController":
                merged.update(sel)
            else:
                merged.update(sel.get("matchLabels") or {})
                exprs.extend(sel.get("matchExpressions") or [])
    if not merged and not exprs:
        return None
    out: JSON = {}
    if merged:
        out["matchLabels"] = merged
    if exprs:
        out["matchExpressions"] = exprs
    return out


def _effective_selector(con: JSON, pod: JSON) -> JSON:
    """labelSelector with matchLabelKeys folded in as In-requirements on
    the pod's own label values (upstream MatchLabelKeysInPodTopologySpread,
    beta/on in v1.30)."""
    sel = dict(con.get("labelSelector") or {})
    keys = con.get("matchLabelKeys") or []
    if keys:
        pod_labels = labels_of(pod)
        exprs = list(sel.get("matchExpressions") or [])
        for k in keys:
            if k in pod_labels:
                exprs.append({"key": k, "operator": "In", "values": [pod_labels[k]]})
        sel["matchExpressions"] = exprs
    return sel


def encode_topology_spread(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    bound_pods: Sequence[JSON],
    n_padded: int,
    p_padded: int,
    *,
    agg: dict | None = None,
    bound_map: "dict[int, JSON] | None" = None,
    changed_slots: "set[int] | None" = None,
    slot_of: "dict[str, int] | None" = None,
    default_constraints: tuple | None = None,
) -> SpreadTensors:
    """``agg``/``bound_map``/``changed_slots``/``slot_of`` come from a
    persistent Featurizer (state/boundagg.py): the selector vocabulary
    then persists append-only across calls and the per-node
    selector-match counts over BOUND pods update by delta.  Without
    ``agg`` every call is a one-shot rebuild (same code path, throwaway
    state)."""
    from ksim_tpu.state.resources import namespace_of
    from ksim_tpu.state.selectors import match_label_selector

    agg = agg if agg is not None else {}
    if bound_map is None:
        bound_map = {id(p): p for p in bound_pods}
    changed_slots = changed_slots if changed_slots is not None else set()

    tk_vocab: dict[str, int] = {}
    dom_vocab: dict[tuple[int, str], int] = {}
    sels = agg.setdefault("spread_sels", {"vocab": {}, "list": []})
    if len(sels["list"]) > 4096:
        # Reset valve (same pattern as the interpod vocabularies): an
        # adversarial stream of distinct selectors must not grow the
        # vocabulary — and the (N x S) count arrays — without bound.
        agg.pop("spread_sels", None)
        agg.pop("spread_init", None)
        sels = agg.setdefault("spread_sels", {"vocab": {}, "list": []})
    sel_vocab: dict[str, int] = sels["vocab"]
    sel_list: list[tuple[str, JSON]] = sels["list"]  # (namespace, selector)

    def tk_id(k: str) -> int:
        if k not in tk_vocab:
            tk_vocab[k] = len(tk_vocab)
        return tk_vocab[k]

    def sel_id_by_key(key: str, ns: str, sel: JSON) -> int:
        if key not in sel_vocab:
            sel_vocab[key] = len(sel_list)
            sel_list.append((ns, sel))
        return sel_vocab[key]

    from ksim_tpu.state import objcache

    defaults_token = _canon(list(default_constraints)) if default_constraints else ""

    def parsed_cons(pod: JSON) -> list[dict]:
        """Vocab-independent constraint parse, memoized per pod object
        (the effective selector and its canonical key are the expensive
        parts; vocab ids are assigned per call).  Pods without their own
        constraints fall back to the profile's defaultConstraints
        (PodTopologySpreadArgs; upstream pod_topology_spread/common.go
        buildDefaultConstraints) — whose selector comes from
        default_spread_selector and is empty in the snapshot model, so
        the fallback yields no constraints (documented there)."""

        def build() -> list[dict]:
            ns = namespace_of(pod) or "default"
            out = []
            own = pod.get("spec", {}).get("topologySpreadConstraints") or []
            cons_src = own
            if not own and default_constraints:
                sel = default_spread_selector(pod)
                if sel is not None:
                    cons_src = [
                        dict(c, labelSelector=sel) for c in default_constraints
                    ]
            for con in cons_src:
                sel = _effective_selector(con, pod)
                out.append(
                    {
                        "tk_str": con.get("topologyKey", ""),
                        "ns": ns,
                        "sel_obj": sel,
                        "sel_key": _canon({"ns": ns, "sel": sel}),
                        "mode": 0 if con.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule" else 1,
                        "max_skew": int(con.get("maxSkew", 1)),
                        "min_domains": int(con.get("minDomains") or 0),
                        "self": match_label_selector(sel, labels_of(pod)),
                        "honor_aff": (con.get("nodeAffinityPolicy") or "Honor") == "Honor",
                        "honor_taints": (con.get("nodeTaintsPolicy") or "Ignore") == "Honor",
                    }
                )
            return out

        return objcache.cached("spreadcons", pod, build, defaults_token)

    # Pass 1: constraint tables.
    per_pod_cons: list[list[dict]] = []
    for pod in pods:
        cons = []
        for c in parsed_cons(pod):
            cons.append(
                dict(c, tk=tk_id(c["tk_str"]), sel=sel_id_by_key(c["sel_key"], c["ns"], c["sel_obj"]))
            )
        per_pod_cons.append(cons)

    TK = max(len(tk_vocab), 1)

    def build_node_domains():
        """Node-domain tables — a pure function of (node list, topology
        -key vocab); ``dom_vocab`` is call-local here (unlike interpod's
        persistent one), so the whole output is cacheable as a family on
        the exact node objects + key token."""
        node_dom = np.full((n_padded, TK), -1, dtype=np.int32)
        node_ldom = np.full((n_padded, TK), -1, dtype=np.int32)
        tk_sizes = [1] * TK
        tk_singleton = [True] * TK
        per_key_loc: list[dict[str, int]] = [{} for _ in range(TK)]
        per_key_cnt: list[dict[int, int]] = [{} for _ in range(TK)]
        for ni, node in enumerate(nodes):
            lbls = labels_of(node)
            for k, ki in tk_vocab.items():
                if k in lbls:
                    dk = (ki, lbls[k])
                    if dk not in dom_vocab:
                        dom_vocab[dk] = len(dom_vocab)
                    node_dom[ni, ki] = dom_vocab[dk]
                    li = per_key_loc[ki].setdefault(lbls[k], len(per_key_loc[ki]))
                    node_ldom[ni, ki] = li
                    per_key_cnt[ki][li] = per_key_cnt[ki].get(li, 0) + 1
        for ki in range(TK):
            tk_sizes[ki] = max(len(per_key_loc[ki]), 1)
            tk_singleton[ki] = all(c <= 1 for c in per_key_cnt[ki].values())
        return node_dom, node_ldom, tk_sizes, tk_singleton, max(len(dom_vocab), 1)

    node_dom, node_ldom, tk_sizes, tk_singleton, n_domains = objcache.cached_seq(
        "enc_spread_nodes", nodes, build_node_domains, tuple(tk_vocab), n_padded
    )

    S = _vpad(len(sel_list))
    S0 = len(sel_list)
    # Per-pod selector-match rows, memoized on (pod object, selector
    # vocab) — the vocab stabilizes under churn, so unchanged pods cost
    # one lookup per pass.
    sels_token = tuple(sel_vocab)
    sels_tok = objcache.intern_token(sels_token)

    def sel_row(pod: JSON) -> np.ndarray:
        key = ("spreadrow", objcache.ref_id(pod), sels_tok)
        hit = objcache.get(key)
        if hit is not objcache.MISS:
            return hit
        pod_ns = namespace_of(pod) or "default"
        pod_labels = labels_of(pod)
        row = np.fromiter(
            (pod_ns == ns and match_label_selector(sel, pod_labels) for ns, sel in sel_list),
            dtype=bool,
            count=S0,
        )
        return objcache.put(key, row)

    from ksim_tpu.state.boundagg import sync_family

    node_index = slot_of if slot_of is not None else {
        name_of(n): i for i, n in enumerate(nodes)
    }
    N0 = len(nodes)

    def _init_record(bp: JSON):
        ni = node_index.get(bp.get("spec", {}).get("nodeName", ""))
        if ni is None or ni >= N0:
            return None
        return (ni, sel_row(bp))

    def _init_apply(arr, rec, sign: int) -> None:
        ni, row = rec
        if sign > 0:
            arr[ni, : row.shape[0]] += row
        else:
            arr[ni, : row.shape[0]] -= row

    init_counts = sync_family(
        agg,
        "spread_init",
        (sels_tok, S, S0, n_padded),
        bound_map,
        changed_slots,
        make_arrays=lambda: np.zeros((n_padded, S), dtype=np.int32),
        record_of=_init_record,
        apply=_init_apply,
    ).copy()

    pod_sel_match = np.zeros((p_padded, S), dtype=bool)
    for j, pod in enumerate(pods):
        pod_sel_match[j, :S0] = sel_row(pod)

    MC = max((len(c) for c in per_pod_cons), default=0)
    MC = _vpad(MC, minimum=2)
    shape = (p_padded, MC)
    con_valid = np.zeros(shape, dtype=bool)
    con_mode = np.zeros(shape, dtype=np.int32)
    con_sel = np.zeros(shape, dtype=np.int32)
    con_tk = np.zeros(shape, dtype=np.int32)
    con_max_skew = np.ones(shape, dtype=np.int32)
    con_min_domains = np.zeros(shape, dtype=np.int32)
    con_self = np.zeros(shape, dtype=bool)
    con_honor_aff = np.ones(shape, dtype=bool)
    con_honor_taints = np.zeros(shape, dtype=bool)
    has_score = np.zeros(p_padded, dtype=bool)
    for j, cons in enumerate(per_pod_cons):
        for ci, c in enumerate(cons):
            con_valid[j, ci] = True
            con_mode[j, ci] = c["mode"]
            con_sel[j, ci] = c["sel"]
            con_tk[j, ci] = c["tk"]
            con_max_skew[j, ci] = c["max_skew"]
            con_min_domains[j, ci] = c["min_domains"]
            con_self[j, ci] = c["self"]
            con_honor_aff[j, ci] = c["honor_aff"]
            con_honor_taints[j, ci] = c["honor_taints"]
            if c["mode"] == 1:
                has_score[j] = True

    return SpreadTensors(
        n_domains=n_domains,
        tk_sizes=tuple(tk_sizes),
        tk_singleton=tuple(tk_singleton),
        node_dom=node_dom,
        node_ldom=node_ldom,
        init_counts=init_counts,
        pod_sel_match=pod_sel_match,
        con_valid=con_valid,
        con_mode=con_mode,
        con_sel=con_sel,
        con_tk=con_tk,
        con_max_skew=con_max_skew,
        con_min_domains=con_min_domains,
        con_self=con_self,
        con_honor_aff=con_honor_aff,
        con_honor_taints=con_honor_taints,
        has_score_con=has_score,
    )
