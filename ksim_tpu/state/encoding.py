"""Vocabulary encodings: labels/selector terms and taints as tensors.

SURVEY.md hard part 5 — "expressing label/taint/affinity matching as
tensors".  The split that keeps semantics exact AND the device path dense:

- **Host side** (here, numpy + exact string matching): build vocabularies
  of distinct selector *requirements* (key, operator, values) and *terms*
  (conjunctions of requirements) across the pod set, evaluate every
  requirement against every node's labels once (Q x N boolean matrix),
  and evaluate each pod's tolerations against the cluster's distinct
  taints (P x W boolean matrix).  All In/NotIn/Exists/DoesNotExist/Gt/Lt
  and toleration operator semantics run in Python — bit-exact by
  construction (state/selectors.py, state/resources.py).
- **Device side** (plugins/nodeaffinity.py, plugins/tainttoleration.py):
  term matching reduces to an integer matmul — a node matches term t iff
  its satisfied-requirement count over the term's requirement set equals
  the term size — and taint filtering/scoring to masked reductions.

Everything here keys into ``FeaturizedSnapshot.aux`` and rides into the
jitted programs as traced inputs (never baked constants).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ksim_tpu.state.resources import (
    JSON,
    labels_of,
    name_of,
    pod_tolerations,
    toleration_tolerates,
)
from ksim_tpu.state.selectors import match_node_selector_requirement

FORBIDDING_EFFECTS = ("NoSchedule", "NoExecute")


# -- node-affinity / node-selector encoding ---------------------------------


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass
class AffinityTensors:
    """Term-algebra arrays for NodeAffinity + pod.spec.nodeSelector."""

    # Leading-axis kind per field, consumed by engine/sharding.shard_aux
    # ("node" -> tp, "pod" -> dp, None -> replicated).
    AXES = {
        "node_req_match": "node",
        "term_req": None,
        "term_size": None,
        "selector_term": "pod",
        "has_required": "pod",
        "required_terms": "pod",
        "preferred_weights": "pod",
    }

    node_req_match: np.ndarray  # bool [N(padded), Q]
    term_req: np.ndarray  # bool [T, Q]
    term_size: np.ndarray  # int32 [T] (-1 for empty terms: match nothing)
    selector_term: np.ndarray  # int32 [P(padded)] index into T, -1 = none
    has_required: np.ndarray  # bool [P]
    required_terms: np.ndarray  # bool [P, T]
    preferred_weights: np.ndarray  # int32 [P, T]

    @property
    def n_terms(self) -> int:
        return self.term_req.shape[0]


class _TermVocab:
    def __init__(self) -> None:
        self.reqs: dict[str, int] = {}
        self.req_list: list[JSON] = []
        self.terms: dict[str, int] = {}
        self.term_list: list[list[int]] = []

    def req_id(self, req: JSON) -> int:
        k = _canon(req)
        if k not in self.reqs:
            self.reqs[k] = len(self.req_list)
            self.req_list.append(req)
        return self.reqs[k]

    def term_id(self, reqs: Sequence[JSON]) -> int:
        ids = sorted(self.req_id(r) for r in reqs)
        k = _canon(ids)
        if k not in self.terms:
            self.terms[k] = len(self.term_list)
            self.term_list.append(ids)
        return self.terms[k]


def _term_reqs_from_selector_term(term: JSON) -> list[JSON] | None:
    """NodeSelectorTerm -> requirement list; None for terms that match
    nothing: the empty term, or a matchFields key other than metadata.name
    (the only supported field — upstream nodeaffinity.go)."""
    reqs = []
    for e in term.get("matchExpressions") or []:
        reqs.append(dict(e))
    for f in term.get("matchFields") or []:
        if f.get("key") != "metadata.name":
            return None
        reqs.append({**f, "_field": True})
    return reqs or None


def encode_affinity(
    nodes: Sequence[JSON], pods: Sequence[JSON], n_padded: int, p_padded: int
) -> AffinityTensors:
    vocab = _TermVocab()
    EMPTY = -2  # sentinel term id for match-nothing terms

    def term_for(term: JSON) -> int:
        reqs = _term_reqs_from_selector_term(term)
        return EMPTY if reqs is None else vocab.term_id(reqs)

    sel_term = np.full(p_padded, -1, dtype=np.int32)
    has_req = np.zeros(p_padded, dtype=bool)
    req_terms: list[list[int]] = [[] for _ in range(p_padded)]
    pref: list[dict[int, int]] = [{} for _ in range(p_padded)]

    for j, pod in enumerate(pods):
        spec = pod.get("spec", {})
        ns = spec.get("nodeSelector")
        if ns:
            reqs = [
                {"key": k, "operator": "In", "values": [v]} for k, v in sorted(ns.items())
            ]
            sel_term[j] = vocab.term_id(reqs)
        aff = (spec.get("affinity") or {}).get("nodeAffinity") or {}
        required = aff.get("requiredDuringSchedulingIgnoredDuringExecution")
        if required is not None:
            has_req[j] = True
            for t in required.get("nodeSelectorTerms") or []:
                tid = term_for(t)
                # Match-nothing terms contribute nothing to the OR.
                if tid != EMPTY:
                    req_terms[j].append(tid)
        for pt in aff.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
            tid = term_for(pt.get("preference") or {})
            if tid != EMPTY:
                w = int(pt.get("weight", 0))
                pref[j][tid] = pref[j].get(tid, 0) + w

    Q = len(vocab.req_list)
    T = len(vocab.term_list)
    node_req_match = np.zeros((n_padded, max(Q, 1)), dtype=bool)
    for ni, node in enumerate(nodes):
        lbls = dict(labels_of(node))
        field_lbls = {"metadata.name": name_of(node)}
        for qi, req in enumerate(vocab.req_list):
            if req.get("_field"):
                r = {k: v for k, v in req.items() if k != "_field"}
                node_req_match[ni, qi] = match_node_selector_requirement(r, field_lbls)
            else:
                node_req_match[ni, qi] = match_node_selector_requirement(req, lbls)

    term_req = np.zeros((max(T, 1), max(Q, 1)), dtype=bool)
    term_size = np.full(max(T, 1), -1, dtype=np.int32)
    for ti, ids in enumerate(vocab.term_list):
        for qi in ids:
            term_req[ti, qi] = True
        term_size[ti] = len(ids)

    required_terms = np.zeros((p_padded, max(T, 1)), dtype=bool)
    preferred_weights = np.zeros((p_padded, max(T, 1)), dtype=np.int32)
    for j in range(p_padded):
        for tid in req_terms[j]:
            required_terms[j, tid] = True
        for tid, w in pref[j].items():
            preferred_weights[j, tid] = w

    return AffinityTensors(
        node_req_match=node_req_match,
        term_req=term_req,
        term_size=term_size,
        selector_term=sel_term,
        has_required=has_req,
        required_terms=required_terms,
        preferred_weights=preferred_weights,
    )


# -- taint / toleration encoding --------------------------------------------


@dataclass
class TaintTensors:
    """Distinct-taint vocabulary arrays."""

    AXES = {
        "node_taint_order": "node",
        "forbidding": None,
        "prefer": None,
        "pod_tolerated": "pod",
        "pod_tolerated_prefer": "pod",
    }

    taints: list[JSON]  # W distinct taints (key, value, effect)
    node_taint_order: np.ndarray  # int32 [N(padded), W], position+1, 0=absent
    forbidding: np.ndarray  # bool [W] effect in (NoSchedule, NoExecute)
    prefer: np.ndarray  # bool [W] effect == PreferNoSchedule
    pod_tolerated: np.ndarray  # bool [P(padded), W] (all tolerations)
    pod_tolerated_prefer: np.ndarray  # bool [P, W] (effect ""|PreferNoSchedule tolerations only)

    @property
    def n_taints(self) -> int:
        return len(self.taints)


def encode_taints(
    nodes: Sequence[JSON], pods: Sequence[JSON], n_padded: int, p_padded: int
) -> TaintTensors:
    vocab: dict[str, int] = {}
    taints: list[JSON] = []

    def tid(t: JSON) -> int:
        key = _canon({"key": t.get("key", ""), "value": t.get("value", ""), "effect": t.get("effect", "")})
        if key not in vocab:
            vocab[key] = len(taints)
            taints.append(
                {"key": t.get("key", ""), "value": t.get("value", ""), "effect": t.get("effect", "")}
            )
        return vocab[key]

    per_node: list[list[int]] = []
    for node in nodes:
        per_node.append([tid(t) for t in node.get("spec", {}).get("taints") or []])

    W = max(len(taints), 1)
    order = np.zeros((n_padded, W), dtype=np.int32)
    for ni, ids in enumerate(per_node):
        for pos, w in enumerate(ids):
            if order[ni, w] == 0:
                order[ni, w] = pos + 1
    forbidding = np.zeros(W, dtype=bool)
    prefer = np.zeros(W, dtype=bool)
    for w, t in enumerate(taints):
        forbidding[w] = t["effect"] in FORBIDDING_EFFECTS
        prefer[w] = t["effect"] == "PreferNoSchedule"

    tolerated = np.zeros((p_padded, W), dtype=bool)
    tolerated_prefer = np.zeros((p_padded, W), dtype=bool)
    for j, pod in enumerate(pods):
        tols = pod_tolerations(pod)
        prefer_tols = [t for t in tols if (t.get("effect") or "") in ("", "PreferNoSchedule")]
        for w, t in enumerate(taints):
            tolerated[j, w] = any(toleration_tolerates(tl, t) for tl in tols)
            tolerated_prefer[j, w] = any(toleration_tolerates(tl, t) for tl in prefer_tols)

    return TaintTensors(
        taints=taints,
        node_taint_order=order,
        forbidding=forbidding,
        prefer=prefer,
        pod_tolerated=tolerated,
        pod_tolerated_prefer=tolerated_prefer,
    )
