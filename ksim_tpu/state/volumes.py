"""Volume-family tensor encodings: VolumeBinding, VolumeZone,
NodeVolumeLimits, VolumeRestrictions.

Semantics re-derived from upstream kube-scheduler v1.30
``plugins/{volumebinding,volumezone,nodevolumelimits,volumerestrictions}``
over what the snapshot model can express (pods, pvs, pvcs,
storageclasses — the reference's 7-kind snapshot,
simulator/snapshot/snapshot.go:33-42; CSINode objects don't exist in
either snapshot model, so attach limits read the node's
``attachable-volumes-*`` allocatable keys, the pre-CSINode mechanism).

Factored host/device split (nothing [P, N]-sized is materialized):

- **VolumeBinding / VolumeZone**: every PV referenced by a queue pod's
  bound PVCs gets a row in ``pv_node_ok`` / ``pv_zone_ok`` [NPV, N]
  (node-affinity and zone-label matching evaluated host-side in exact
  Python); a pod's per-node verdict is then a ``[NPV] x [NPV, N]`` dot.
  Unbound WaitForFirstConsumer PVCs get candidate-PV node masks
  ``pvc_cand_ok`` [C, N] + a node-independent ``provisionable`` flag.
  Pod-level failures (unbound Immediate PVC, missing PVC) fail every
  node with a dedicated bit, like upstream's PreFilter
  UnschedulableAndUnresolvable abort.
- **NodeVolumeLimits**: volume vocabulary V (distinct PVC-backed volume
  ids) with a key id per volume (which ``attachable-volumes-<k>`` pool
  it consumes, from the PV source or the StorageClass provisioner);
  per-node attached [N, V] counts (the scan carry) + per-node limits
  [N, K]; new-attachment counting dedups volumes already attached to
  the node, exactly like upstream's unique-volume counting.
- **VolumeRestrictions**: ReadWriteOncePod PVC vocabulary R and direct
  disk-source vocabulary D (GCE PD / AWS EBS / ISCSI / RBD ids):
  per-node use counts (any/rw) as carries; GCE/ISCSI/RBD allow
  read-only sharing, EBS never shares (upstream isVolumeConflict).

Documented simplifications: ephemeral volume claims use the upstream
``<pod>-<volume>`` naming but ownership is not verified; dynamic
provisioning treats any StorageClass with a real provisioner (not
``kubernetes.io/no-provisioner``) as satisfiable without capacity
tracking (upstream needs CSIStorageCapacity objects the snapshot lacks).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ksim_tpu.state.resources import JSON, labels_of, name_of, namespace_of
from ksim_tpu.state.selectors import match_node_selector_terms
from ksim_tpu.state.featurizer import vocab_pad

# Zone/region label keys upstream volume_zone.go consults.
ZONE_KEYS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)

NO_PROVISIONER = "kubernetes.io/no-provisioner"

# Direct volume sources with attach-conflict rules (upstream
# volumerestrictions isVolumeConflict): (spec key, id field, ro-shareable)
DISK_SOURCES = (
    ("gcePersistentDisk", "pdName", True),
    ("awsElasticBlockStore", "volumeID", False),
    ("iscsi", "iqn", True),
    ("rbd", "rbdImage", True),
)

# Sources that consume an attach-limit pool but have NO conflict rule
# (upstream nodevolumelimits counts azure disks and cinder volumes;
# volumerestrictions doesn't restrict them).
LIMIT_ONLY_SOURCES = (("azureDisk", "diskName"), ("cinder", "volumeID"))

# Attachable-volume pools (pre-CSINode node allocatable keys) per source.
# Pool names double as the per-plugin split for the legacy registry names
# (upstream nodevolumelimits non_csi.go registers EBSLimits/GCEPDLimits/
# AzureDiskLimits/CinderLimits as one-type filters; the reference's
# exported default config carries them, snapshot_test.go:1415).
SOURCE_POOL = {
    "gcePersistentDisk": "gce-pd",
    "awsElasticBlockStore": "aws-ebs",
    "azureDisk": "azure-disk",
    "cinder": "cinder",
}


@dataclass
class VolumeTensors:
    AXES = {
        "pv_node_ok": None,  # [NPV, N] — N is the MINOR axis here
        "pv_zone_ok": None,
        "pvc_cand_ok": None,
        "pvc_provisionable": None,
        "pod_pv": "pod",
        "pod_wffc": "pod",
        "pod_fail": "pod",
        "attached_init": "node",
        "limits": "node",
        "vol_key": None,
        "pod_vol": "pod",
        "rwop_init": "node",
        "pod_rwop": "pod",
        "disk_any_init": "node",
        "disk_rw_init": "node",
        "pod_disk_any": "pod",
        "pod_disk_rw": "pod",
        "disk_ro_shareable": None,
    }

    # VolumeBinding + VolumeZone
    pv_node_ok: np.ndarray  # bool [NPV, N] PV node-affinity admits node
    pv_zone_ok: np.ndarray  # bool [NPV, N] PV zone labels admit node
    pvc_cand_ok: np.ndarray  # bool [C, N] some available PV binds on node
    pvc_provisionable: np.ndarray  # bool [C] SC can dynamically provision
    pod_pv: np.ndarray  # bool [P, NPV] pod's bound PVCs' PVs
    pod_wffc: np.ndarray  # bool [P, C] pod's unbound WFFC PVCs
    pod_fail: np.ndarray  # i32 [P] bitmask: 1 unbound-immediate | 2 pvc-missing
    # NodeVolumeLimits
    attached_init: np.ndarray  # i32 [N, V] volume attached to node (carry)
    limits: np.ndarray  # i32 [N, K] pool limits (-1 = unlimited)
    vol_key: np.ndarray  # i32 [V] volume -> pool id (-1 = uncounted)
    pod_vol: np.ndarray  # bool [P, V] pod uses volume
    # VolumeRestrictions
    rwop_init: np.ndarray  # i32 [N, R] RWOP-claim users on node (carry)
    pod_rwop: np.ndarray  # bool [P, R]
    disk_any_init: np.ndarray  # i32 [N, D] any-mode users (carry)
    disk_rw_init: np.ndarray  # i32 [N, D] rw users (carry)
    pod_disk_any: np.ndarray  # bool [P, D] pod uses disk (any mode)
    pod_disk_rw: np.ndarray  # bool [P, D] pod uses disk read-write
    disk_ro_shareable: np.ndarray  # bool [D] both-read-only sharing allowed
    n_pools: int  # K (static info)
    # Pool id -> attachable-volumes-* suffix (static info): lets the
    # legacy per-type plugins (EBSLimits et al.) restrict their check to
    # one pool while NodeVolumeLimits covers all of them.
    pool_names: tuple[str, ...] = ()


_EMPTY_ROW = {"pv": (), "wffc": (), "vol": (), "rwop": (), "disk": (), "fail": 0}


def _pod_volumes(pod: JSON) -> list[JSON]:
    return pod.get("spec", {}).get("volumes") or []


def _pod_has_volumes(pod: JSON) -> bool:
    """Memoized per pod object: churn replay re-checks every bound pod
    each pass, and the common case is volume-free pods."""
    from ksim_tpu.state import objcache

    return objcache.cached(
        "has_vols", pod, lambda: bool(_pod_volumes(pod))
    )


def _node_has_attach_pools(node: JSON) -> bool:
    """Memoized per node object: does the node expose any
    attachable-volumes-* allocatable key?"""
    from ksim_tpu.state import objcache

    def build() -> bool:
        alloc = node.get("status", {}).get("allocatable") or {}
        return any(k.startswith("attachable-volumes-") for k in alloc)

    return objcache.cached("attach_pools", node, build)


def _any_node_has_attach_pools(nodes) -> bool:
    """Family-memoized over the exact node list: the volumes fast path
    asks this every pass, and walking 2k per-node memos was a measurable
    slice of churn featurize time."""
    from ksim_tpu.state import objcache

    return objcache.cached_seq(
        "any_attach_pools",
        nodes,
        lambda: any(_node_has_attach_pools(n) for n in nodes),
    )


# Trivial no-volume tensors per (n_padded, p_padded): identical arrays
# across passes (stable host buffers; nothing to rebuild).
_TRIVIAL: dict = {}


def _trivial_volume_tensors(n_padded: int, p_padded: int) -> "VolumeTensors":
    hit = _TRIVIAL.get((n_padded, p_padded))
    if hit is not None:
        return hit
    from ksim_tpu.state.featurizer import vocab_pad

    NPV = C = V = R = D = vocab_pad(0)
    K = 1
    out = VolumeTensors(
        pv_node_ok=np.ones((NPV, n_padded), dtype=bool),
        pv_zone_ok=np.ones((NPV, n_padded), dtype=bool),
        pvc_cand_ok=np.zeros((C, n_padded), dtype=bool),
        pvc_provisionable=np.zeros(C, dtype=bool),
        pod_pv=np.zeros((p_padded, NPV), dtype=bool),
        pod_wffc=np.zeros((p_padded, C), dtype=bool),
        pod_fail=np.zeros(p_padded, dtype=np.int32),
        attached_init=np.zeros((n_padded, V), dtype=np.int32),
        limits=np.full((n_padded, K), -1, dtype=np.int32),
        vol_key=np.full(V, -1, dtype=np.int32),
        pod_vol=np.zeros((p_padded, V), dtype=bool),
        rwop_init=np.zeros((n_padded, R), dtype=np.int32),
        pod_rwop=np.zeros((p_padded, R), dtype=bool),
        disk_any_init=np.zeros((n_padded, D), dtype=np.int32),
        disk_rw_init=np.zeros((n_padded, D), dtype=np.int32),
        pod_disk_any=np.zeros((p_padded, D), dtype=bool),
        pod_disk_rw=np.zeros((p_padded, D), dtype=bool),
        disk_ro_shareable=np.zeros(D, dtype=bool),
        n_pools=1,
        pool_names=("",),
    )
    if len(_TRIVIAL) > 64:
        _TRIVIAL.clear()
    _TRIVIAL[(n_padded, p_padded)] = out
    return out


def _pvc_name(pod: JSON, vol: JSON) -> str | None:
    """PVC claim name for a volume: persistentVolumeClaim or ephemeral
    (upstream ephemeral.VolumeClaimName: <pod>-<volume>)."""
    pvc = vol.get("persistentVolumeClaim")
    if pvc and pvc.get("claimName"):
        return pvc["claimName"]
    if vol.get("ephemeral"):
        return f"{name_of(pod)}-{vol.get('name', '')}"
    return None


def _pv_zone_admits(pv: JSON, node_labels: dict) -> bool:
    """volume_zone.go: for each zone/region label on the PV, the node
    must carry the key with a value in the PV's __-separated set."""
    pv_labels = labels_of(pv)
    for key in ZONE_KEYS:
        if key not in pv_labels:
            continue
        allowed = set(str(pv_labels[key]).split("__"))
        if node_labels.get(key) not in allowed:
            return False
    return True


def _pv_affinity_admits(pv: JSON, node: JSON) -> bool:
    req = (
        (pv.get("spec") or {}).get("nodeAffinity") or {}
    ).get("required")
    if not req:
        return True
    return match_node_selector_terms(
        req.get("nodeSelectorTerms") or [], dict(labels_of(node)), name_of(node)
    )


def _pv_matches_claim(pv: JSON, pvc: JSON) -> bool:
    """Static binding match (upstream pv_controller findMatchingVolume,
    reduced): class, access modes, capacity, phase Available, no claimRef."""
    spec = pv.get("spec") or {}
    if (pv.get("status") or {}).get("phase") not in ("Available", None):
        return False
    if spec.get("claimRef"):
        return False
    pvc_spec = pvc.get("spec") or {}
    if (spec.get("storageClassName") or "") != (pvc_spec.get("storageClassName") or ""):
        return False
    want_modes = set(pvc_spec.get("accessModes") or [])
    if want_modes and not want_modes.issubset(set(spec.get("accessModes") or [])):
        return False
    from ksim_tpu.state.quantity import parse_quantity

    want = (pvc_spec.get("resources") or {}).get("requests", {}).get("storage")
    have = (spec.get("capacity") or {}).get("storage")
    if want is not None:
        if have is None:
            return False
        if parse_quantity(have).raw < parse_quantity(want).raw:
            return False
    return True


def encode_volumes(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    bound_pods: Sequence[JSON],
    pvs: Sequence[JSON],
    pvcs: Sequence[JSON],
    storage_classes: Sequence[JSON],
    n_padded: int,
    p_padded: int,
    *,
    bound_volume_free: "bool | None" = None,
) -> VolumeTensors:
    # Fast path — the common churn case: no volume API objects, no pod
    # declares volumes, no node exposes attach pools.  The bound-pod scan
    # is the expensive precondition at churn scale; a persistent
    # Featurizer passes ``bound_volume_free`` from its incrementally
    # maintained count instead.
    if (
        not pvs
        and not pvcs
        and not storage_classes
        and not any(_pod_has_volumes(p) for p in pods)
        and (
            bound_volume_free
            if bound_volume_free is not None
            else not any(_pod_has_volumes(p) for p in bound_pods)
        )
        and not _any_node_has_attach_pools(nodes)
    ):
        return _trivial_volume_tensors(n_padded, p_padded)

    pvc_by_key = {f"{namespace_of(c)}/{name_of(c)}": c for c in pvcs}
    pv_by_name = {name_of(v): v for v in pvs}
    sc_by_name = {name_of(s): s for s in storage_classes}

    def sc_of(pvc: JSON) -> JSON | None:
        return sc_by_name.get((pvc.get("spec") or {}).get("storageClassName") or "")

    def binding_mode(pvc: JSON) -> str:
        sc = sc_of(pvc)
        if sc is None:
            return "Immediate"
        return sc.get("volumeBindingMode") or "Immediate"

    def provisionable(pvc: JSON) -> bool:
        sc = sc_of(pvc)
        return bool(sc and (sc.get("provisioner") or "") not in ("", NO_PROVISIONER))

    # Vocabularies built from the QUEUE pods' volume usage.
    pv_vocab: dict[str, int] = {}  # PV name -> row
    wffc_vocab: dict[str, int] = {}  # pvc key -> row
    vol_vocab: dict[str, int] = {}  # attachable volume id -> row
    vol_key_of: dict[str, str] = {}  # volume id -> pool key
    rwop_vocab: dict[str, int] = {}  # RWOP pvc key -> row
    disk_vocab: dict[tuple[str, str], int] = {}  # (source, id) -> row

    pod_fail = np.zeros(p_padded, dtype=np.int32)
    pod_rows: list[dict] = []

    def classify_pod(pod: JSON, register: bool):
        """Walk a pod's volumes; returns per-pod row dict (queue pods).
        Pods without volumes (the common churn case) share one frozen
        empty row — consumers only iterate the rows."""
        vols = _pod_volumes(pod)
        if not vols:
            return _EMPTY_ROW
        ns = namespace_of(pod) or "default"
        row = {"pv": [], "wffc": [], "vol": [], "rwop": [], "disk": []}
        fail = 0
        for vol in vols:
            claim = _pvc_name(pod, vol)
            if claim is not None:
                pvc = pvc_by_key.get(f"{ns}/{claim}")
                if pvc is None:
                    fail |= 2  # pvc not found
                    continue
                modes = set((pvc.get("spec") or {}).get("accessModes") or [])
                if "ReadWriteOncePod" in modes:
                    key = f"{ns}/{claim}"
                    if register:
                        rwop_vocab.setdefault(key, len(rwop_vocab))
                    if key in rwop_vocab:
                        row["rwop"].append(rwop_vocab[key])
                bound_pv = (pvc.get("spec") or {}).get("volumeName") or ""
                if bound_pv:
                    pv = pv_by_name.get(bound_pv)
                    if pv is None:
                        fail |= 2
                        continue
                    if register:
                        pv_vocab.setdefault(bound_pv, len(pv_vocab))
                    if bound_pv in pv_vocab:
                        row["pv"].append(pv_vocab[bound_pv])
                    # Attach-limit accounting for the PV's source.
                    src, vid = _pv_source_id(pv)
                    if src is not None:
                        pool = SOURCE_POOL.get(src) or _csi_pool(pv, sc_of(pvc))
                        _register_vol(
                            vol_vocab, vol_key_of, f"pv:{bound_pv}", pool, register
                        )
                        if f"pv:{bound_pv}" in vol_vocab:
                            row["vol"].append(vol_vocab[f"pv:{bound_pv}"])
                    else:
                        pool = _csi_pool(pv, sc_of(pvc))
                        _register_vol(
                            vol_vocab, vol_key_of, f"pv:{bound_pv}", pool, register
                        )
                        if f"pv:{bound_pv}" in vol_vocab:
                            row["vol"].append(vol_vocab[f"pv:{bound_pv}"])
                elif binding_mode(pvc) == "Immediate":
                    fail |= 1  # unbound immediate claim
                else:  # WaitForFirstConsumer
                    key = f"{ns}/{claim}"
                    if register:
                        wffc_vocab.setdefault(key, len(wffc_vocab))
                    if key in wffc_vocab:
                        row["wffc"].append(wffc_vocab[key])
                continue
            for src, id_field, _ro in DISK_SOURCES:
                s = vol.get(src)
                if s and s.get(id_field):
                    dk = (src, str(s[id_field]))
                    if register:
                        disk_vocab.setdefault(dk, len(disk_vocab))
                    if dk in disk_vocab:
                        row["disk"].append(
                            (disk_vocab[dk], not bool(s.get("readOnly")))
                        )
                    pool = SOURCE_POOL.get(src)
                    _register_vol(
                        vol_vocab, vol_key_of, f"{src}:{s[id_field]}", pool, register
                    )
                    if f"{src}:{s[id_field]}" in vol_vocab:
                        row["vol"].append(vol_vocab[f"{src}:{s[id_field]}"])
            for src, id_field in LIMIT_ONLY_SOURCES:
                s = vol.get(src)
                if s and s.get(id_field):
                    pool = SOURCE_POOL.get(src)
                    _register_vol(
                        vol_vocab, vol_key_of, f"{src}:{s[id_field]}", pool, register
                    )
                    if f"{src}:{s[id_field]}" in vol_vocab:
                        row["vol"].append(vol_vocab[f"{src}:{s[id_field]}"])
        row["fail"] = fail
        return row

    for j, pod in enumerate(pods):
        row = classify_pod(pod, register=True)
        pod_rows.append(row)
        pod_fail[j] = row["fail"]

    # Bound pods register too: their attached volumes / disk uses / RWOP
    # claims must exist in the vocabularies for the per-node counts even
    # when no queue pod shares them (attach limits count ALL attachments).
    bound_rows = [classify_pod(bp, register=True) for bp in bound_pods]

    # Pool-key vocabulary: every attachable-volumes-* key any node exposes
    # plus any pool a volume maps to.
    pool_vocab: dict[str, int] = {}
    for n in nodes:
        for k in (n.get("status", {}).get("allocatable") or {}):
            if k.startswith("attachable-volumes-"):
                pool_vocab.setdefault(k.removeprefix("attachable-volumes-"), len(pool_vocab))
    for pool in set(vol_key_of.values()):
        if pool:
            pool_vocab.setdefault(pool, len(pool_vocab))

    NPV = vocab_pad(len(pv_vocab))
    C = vocab_pad(len(wffc_vocab))
    V = vocab_pad(len(vol_vocab))
    R = vocab_pad(len(rwop_vocab))
    D = vocab_pad(len(disk_vocab))
    K = max(len(pool_vocab), 1)

    node_labels = [dict(labels_of(n)) for n in nodes]
    pv_node_ok = np.ones((NPV, n_padded), dtype=bool)
    pv_zone_ok = np.ones((NPV, n_padded), dtype=bool)
    for pv_name, vi in pv_vocab.items():
        pv = pv_by_name[pv_name]
        for ni, node in enumerate(nodes):
            pv_node_ok[vi, ni] = _pv_affinity_admits(pv, node)
            pv_zone_ok[vi, ni] = _pv_zone_admits(pv, node_labels[ni])

    pvc_cand_ok = np.zeros((C, n_padded), dtype=bool)
    pvc_provisionable = np.zeros(C, dtype=bool)
    for key, ci in wffc_vocab.items():
        pvc = pvc_by_key[key]
        pvc_provisionable[ci] = provisionable(pvc)
        cands = [pv for pv in pvs if _pv_matches_claim(pv, pvc)]
        for ni, node in enumerate(nodes):
            pvc_cand_ok[ci, ni] = any(
                _pv_affinity_admits(pv, node) for pv in cands
            )

    pod_pv = np.zeros((p_padded, NPV), dtype=bool)
    pod_wffc = np.zeros((p_padded, C), dtype=bool)
    pod_vol = np.zeros((p_padded, V), dtype=bool)
    pod_rwop = np.zeros((p_padded, R), dtype=bool)
    pod_disk_any = np.zeros((p_padded, D), dtype=bool)
    pod_disk_rw = np.zeros((p_padded, D), dtype=bool)
    for j, row in enumerate(pod_rows):
        for vi in row["pv"]:
            pod_pv[j, vi] = True
        for ci in row["wffc"]:
            pod_wffc[j, ci] = True
        for vi in row["vol"]:
            pod_vol[j, vi] = True
        for ri in row["rwop"]:
            pod_rwop[j, ri] = True
        for di, rw in row["disk"]:
            pod_disk_any[j, di] = True
            if rw:
                pod_disk_rw[j, di] = True

    # Per-node initial state from bound pods.
    attached = np.zeros((n_padded, V), dtype=np.int32)
    rwop_init = np.zeros((n_padded, R), dtype=np.int32)
    disk_any = np.zeros((n_padded, D), dtype=np.int32)
    disk_rw = np.zeros((n_padded, D), dtype=np.int32)
    node_index = {name_of(n): i for i, n in enumerate(nodes)}
    for bp, row in zip(bound_pods, bound_rows):
        ni = node_index.get(bp.get("spec", {}).get("nodeName", ""))
        if ni is None:
            continue
        for vi in row["vol"]:
            attached[ni, vi] = 1  # attachment is unique per (volume, node)
        for ri in row["rwop"]:
            rwop_init[ni, ri] += 1
        for di, rw in row["disk"]:
            disk_any[ni, di] += 1
            if rw:
                disk_rw[ni, di] += 1

    limits = np.full((n_padded, K), -1, dtype=np.int32)
    for ni, node in enumerate(nodes):
        alloc = node.get("status", {}).get("allocatable") or {}
        for k, v in alloc.items():
            if k.startswith("attachable-volumes-"):
                pool = k.removeprefix("attachable-volumes-")
                if pool in pool_vocab:
                    limits[ni, pool_vocab[pool]] = int(v)

    vol_key = np.full(V, -1, dtype=np.int32)
    for vid, vi in vol_vocab.items():
        pool = vol_key_of.get(vid)
        if pool and pool in pool_vocab:
            vol_key[vi] = pool_vocab[pool]

    disk_ro_shareable = np.zeros(D, dtype=bool)
    ro_by_src = {src: ro for src, _f, ro in DISK_SOURCES}
    for (src, _id), di in disk_vocab.items():
        disk_ro_shareable[di] = ro_by_src[src]

    return VolumeTensors(
        pv_node_ok=pv_node_ok,
        pv_zone_ok=pv_zone_ok,
        pvc_cand_ok=pvc_cand_ok,
        pvc_provisionable=pvc_provisionable,
        pod_pv=pod_pv,
        pod_wffc=pod_wffc,
        pod_fail=pod_fail,
        attached_init=attached,
        limits=limits,
        vol_key=vol_key,
        pod_vol=pod_vol,
        rwop_init=rwop_init,
        pod_rwop=pod_rwop,
        disk_any_init=disk_any,
        disk_rw_init=disk_rw,
        pod_disk_any=pod_disk_any,
        pod_disk_rw=pod_disk_rw,
        disk_ro_shareable=disk_ro_shareable,
        n_pools=K,
        pool_names=tuple(
            sorted(pool_vocab, key=pool_vocab.get) + [""] * (K - len(pool_vocab))
        ),
    )


def _register_vol(vocab, key_of, vid: str, pool: str | None, register: bool) -> None:
    if register:
        vocab.setdefault(vid, len(vocab))
        if pool:
            key_of[vid] = pool


def _pv_source_id(pv: JSON) -> tuple[str | None, str | None]:
    spec = pv.get("spec") or {}
    for src, id_field, _ro in DISK_SOURCES:
        s = spec.get(src)
        if s and s.get(id_field):
            return src, str(s[id_field])
    for src, id_field in LIMIT_ONLY_SOURCES:
        s = spec.get(src)
        if s and s.get(id_field):
            return src, str(s[id_field])
    return None, None


def _csi_pool(pv: JSON, sc: JSON | None) -> str | None:
    """CSI-backed volumes consume attachable-volumes-csi-<driver>."""
    csi = (pv.get("spec") or {}).get("csi")
    driver = (csi or {}).get("driver") or (sc or {}).get("provisioner")
    if driver and driver != NO_PROVISIONER:
        return f"csi-{driver}"
    return None
