"""In-memory watchable cluster store.

Replaces the reference's KWOK kube-apiserver + etcd pair (reference
compose.yml `simulator-cluster`, kwok.yaml) for library and server use: a
versioned object store for the 7 simulated resource kinds with
list/watch semantics (the reference's client-go RetryWatcher + SSE pipeline,
simulator/resourcewatcher/resourcewatcher.go:61-120, consumes exactly this
event shape), optimistic-concurrency updates (resourceVersion), and
snapshot/restore used by the reset service (reference
simulator/reset/reset.go:33-85 snapshots the etcd prefix the same way).
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import copy
import itertools
import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from ksim_tpu.errors import ConflictError, ExpiredError, NotFoundError
from ksim_tpu.obs import TRACE
from ksim_tpu.state.resources import JSON, name_of, namespace_of

# Kind names follow the reference's watcher kinds
# (simulator/resourcewatcher/resourcewatcher.go:63-71).
KINDS = (
    "pods",
    "nodes",
    "persistentvolumes",
    "persistentvolumeclaims",
    "storageclasses",
    "priorityclasses",
    "namespaces",
)
NAMESPACED_KINDS = frozenset({"pods", "persistentvolumeclaims"})

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


#: pre-image marker for keys a transaction CREATED (nothing to restore).
_MISSING = object()


@dataclass
class _Txn:
    """Open-transaction state: first-touch pre-images + buffered events.

    Pre-images are the LIVE stored dicts (frozen contract: writes
    replace, never mutate), so recording them is O(1) per touched key —
    no copies.  Events buffer instead of delivering; commit replays
    them through the normal notify path, rollback drops them, so a
    watcher (the scheduler loop, the live write-back) can never observe
    a state the transaction did not commit."""

    pre: dict = field(default_factory=dict)  # (kind, key) -> obj | _MISSING
    events: list = field(default_factory=list)
    # True for the device-replay segment reconcile: its writes are the
    # segment's OWN deltas, which the replay lower-cache already tracks,
    # so they must not bump the mutation epoch (see ClusterStore
    # docstring / mutation_epoch).
    epoch_exempt: bool = False


@dataclass(frozen=True, slots=True)
class WatchEvent:
    """Mirrors the reference's streamwriter.WatchEvent
    (simulator/resourcewatcher/streamwriter/streamwriter.go:18-23)."""

    kind: str
    event_type: str
    obj: JSON

    def to_json(self) -> JSON:
        return {"Kind": self.kind, "EventType": self.event_type, "Obj": self.obj}


def _key(kind: str, obj_or_name: JSON | str, namespace: str = "") -> str:
    if isinstance(obj_or_name, str):
        name = obj_or_name
        ns = namespace
    else:
        name = name_of(obj_or_name)
        ns = namespace_of(obj_or_name)
    if kind in NAMESPACED_KINDS:
        return f"{ns or 'default'}/{name}"
    return name


class ClusterStore:
    """Thread-safe versioned store of cluster objects with watch streams."""

    # Watch-resume history depth: older lastResourceVersions trigger a
    # relist, like an etcd compaction would.
    HISTORY_DEPTH = 8192

    def __init__(self, *, strict: "bool | None" = None) -> None:
        self._lock = threading.RLock()
        # Sanitizer-lite (docs/lint.md "Lock discipline", docs/env.md):
        # strict mode makes every internal mutator assert the store
        # lock is held by the calling thread.  Debug-only, off by
        # default; KSIM_STORE_STRICT=1 flips the default (the
        # concurrency-stress tests and the make-faults matrix run with
        # it on).
        self._strict = (
            os.environ.get("KSIM_STORE_STRICT", "") == "1" if strict is None else strict
        )
        self._rv = itertools.count(1)  # guarded-by: _lock
        self._objects: dict[str, dict[str, JSON]] = {k: {} for k in KINDS}  # guarded-by: _lock
        self._watchers: list[tuple[queue.SimpleQueue, frozenset[str]]] = []  # guarded-by: _lock
        # guarded-by: _lock
        self._history: "collections.deque[tuple[int, WatchEvent]]" = (
            collections.deque(maxlen=self.HISTORY_DEPTH)
        )
        # Name-sorted (name, key) order per kind, maintained INCREMENTALLY
        # (bisect insert/remove on membership changes; updates keep their
        # key).  The scheduler lists every kind every pass and churn
        # replay mutates membership every step — re-sorting thousands of
        # unchanged objects per list() dominated churn-replay host time.
        self._sorted_keys: dict[str, list[tuple[str, str]]] = {k: [] for k in KINDS}  # guarded-by: _lock
        # Pod partition by spec.nodeName presence (phase-agnostic; the
        # consumers apply their own phase/queue predicates).  The
        # scheduler walks "all pods" several times per pass only to pick
        # one side of this split — at churn scale those O(pods) walks
        # over a 15k+ population dominated saturated host time.  Values
        # are the same live frozen dicts ``_objects`` holds.
        self._with_node: dict[str, JSON] = {}  # guarded-by: _lock
        self._without_node: dict[str, JSON] = {}  # guarded-by: _lock
        # Secondary index: nodeName -> {pod key -> live obj}.  Node-drain
        # requeue asks "which pods are bound to THESE nodes" — walking
        # the whole bound side per drained node (~10s of the 50k churn
        # replay) against a dict-bucket lookup.
        self._by_node: dict[str, dict[str, JSON]] = {}  # guarded-by: _lock
        self._node_of: dict[str, str] = {}  # guarded-by: _lock
        # Open transaction (``transaction()``); None outside one.
        self._txn: _Txn | None = None  # guarded-by: _lock
        # Mutation epoch: bumped by EVERY write except those staged in an
        # ``epoch_exempt`` transaction (the device-replay segment
        # reconcile, whose deltas the ReplayDriver's lower-cache tracks
        # itself).  The cache keys its validity on this counter: any
        # out-of-band write — a server handler, the write-back loop, a
        # per-pass fallback step, test scaffolding — moves the epoch and
        # strictly invalidates the cached lowered universe at the next
        # segment lower (engine/replay.py _LowerCache).
        self._mutation_epoch = 0  # guarded-by: _lock

    @property
    def mutation_epoch(self) -> int:
        with self._lock:
            return self._mutation_epoch

    # -- transactions -------------------------------------------------------

    # Machine-checked acquisition order (tools/ksimlint lock-order):
    # commit/rollback emit trace events while holding the store lock —
    # the trace plane is a leaf under it.
    # ksimlint: lock-order(ClusterStore._lock<TracePlane._lock)
    @contextlib.contextmanager
    def transaction(self, *, epoch_exempt: bool = False):
        """All-or-nothing write batch.

        Holds the store lock for the whole block (readers in OTHER
        threads wait; the owning thread reads its own staged state
        through the normal API).  On normal exit, buffered watch events
        deliver in write order.  On ANY exception, every touched key
        restores to its pre-transaction object and no event is ever
        delivered — a watcher cannot observe a half-applied batch.
        The resourceVersion counter is deliberately not rewound
        (rv gaps are legal, like etcd revisions).

        Used by the device-replay segment reconcile (scenario/runner.py)
        so an injected mid-reconcile fault — or a parity-check failure —
        can never leave a partially applied segment in the store.
        ``epoch_exempt=True`` (the segment reconcile only) keeps the
        batch's writes from bumping ``mutation_epoch``: the replay
        lower-cache tracks those deltas itself, and only OUT-OF-BAND
        writes must invalidate it.  Nesting is not supported;
        ``restore`` inside a transaction is refused."""
        with self._lock:
            if self._txn is not None:
                raise RuntimeError("nested store transactions are not supported")
            txn = _Txn(epoch_exempt=epoch_exempt)
            self._txn = txn
            try:
                yield self
            except BaseException as e:
                self._txn = None
                self._rollback(txn)
                TRACE.event(
                    "store.txn_rollback",
                    writes=len(txn.pre),
                    events=len(txn.events),
                    error=type(e).__name__,
                )
                raise
            self._txn = None
            TRACE.event(
                "store.txn_commit", writes=len(txn.pre), events=len(txn.events)
            )
            for ev in txn.events:
                self._deliver(ev)

    def _assert_owned(self) -> None:
        """Sanitizer-lite hook (strict mode): raise if the calling
        thread does not hold the store lock.  ``_is_owned`` is the
        stdlib RLock's own ownership probe — private but stable, and
        the only way to ask without trying to acquire."""
        if self._strict and not self._lock._is_owned():
            raise AssertionError(
                "ClusterStore internal mutator called without holding the "
                "store lock (KSIM_STORE_STRICT)"
            )

    def _touch(self, kind: str, key: str) -> None:  # ksimlint: lock-held(_lock)
        """Record a key's first-touch pre-image (callers hold the lock
        and are about to mutate the key)."""
        self._assert_owned()
        txn = self._txn
        if txn is not None and (kind, key) not in txn.pre:
            txn.pre[(kind, key)] = self._objects[kind].get(key, _MISSING)

    def _rollback(self, txn: _Txn) -> None:  # ksimlint: lock-held(_lock)
        """Restore every touched key to its pre-transaction object and
        repair the incremental indexes (callers hold the lock).  The
        (name, key) sort entry is identical for pre/current objects of
        the same key (the key embeds the name), so membership-only
        repair is exact."""
        self._assert_owned()
        for (kind, key), pre in txn.pre.items():
            cur = self._objects[kind].get(key, _MISSING)
            if cur is pre:
                continue
            sk = self._sorted_keys[kind]
            if cur is not _MISSING:
                del self._objects[kind][key]
                entry = (name_of(cur), key)
                idx = bisect.bisect_left(sk, entry)
                if idx < len(sk) and sk[idx] == entry:
                    del sk[idx]
            if pre is not _MISSING:
                self._objects[kind][key] = pre
                bisect.insort(sk, (name_of(pre), key))
            if kind == "pods":
                self._index_pod(key, None if pre is _MISSING else pre)

    # -- pod node-name index ------------------------------------------------

    def _index_pod(self, key: str, obj: JSON | None) -> None:  # ksimlint: lock-held(_lock)
        """Maintain the nodeName partition (callers hold the lock)."""
        self._assert_owned()
        self._with_node.pop(key, None)
        self._without_node.pop(key, None)
        old_node = self._node_of.pop(key, None)
        if old_node is not None:
            bucket = self._by_node.get(old_node)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self._by_node[old_node]
        if obj is None:
            return
        node = obj.get("spec", {}).get("nodeName")
        if node:
            self._with_node[key] = obj
            self._by_node.setdefault(node, {})[key] = obj
            self._node_of[key] = node
        else:
            self._without_node[key] = obj

    # The sides are deliberately UNORDERED (dict insertion order):
    # maintaining incremental (name, key) orders costs an O(side)
    # memmove per pod transition (bind = delete+insert on 15k-entry
    # lists), which measured out slower than the walks the partition
    # saves, and a per-call sort of the bound side costs the same again.
    # Order-sensitive consumers sort the (small) subset they select.

    def pods_with_node(self) -> list[JSON]:
        """Live dicts of pods carrying spec.nodeName (ANY phase),
        UNORDERED.  Read-only, same liveness contract as
        ``list(copy_objs=False)``."""
        with self._lock:
            return list(self._with_node.values())

    def pods_on_nodes(self, node_names) -> list[JSON]:
        """Live dicts of pods bound to any of ``node_names`` (ANY
        phase), UNORDERED — same read-only/liveness contract as
        ``pods_with_node``, via the nodeName bucket index."""
        with self._lock:
            out: list[JSON] = []
            for n in node_names:
                bucket = self._by_node.get(n)
                if bucket:
                    out.extend(bucket.values())
            return out

    def pods_without_node(self) -> list[JSON]:
        """Live dicts of pods without spec.nodeName (ANY phase),
        (name, key)-sorted — the scheduling queue's stable pre-order;
        the pending side is small, so the sort is cheap."""
        with self._lock:
            return [
                o
                for _n, _k, o in sorted(
                    (name_of(o), k, o) for k, o in self._without_node.items()
                )
            ]

    # -- CRUD ---------------------------------------------------------------

    def create(self, kind: str, obj: JSON, *, copy_obj: bool = True) -> JSON:
        """``copy_obj=False`` is the ownership-transfer fast path for
        trusted bulk writers (the scenario runner creates tens of
        thousands of generator-fresh objects; two deepcopies per create
        were ~11% of the 50k churn replay): the caller hands the dict
        over and must neither mutate it afterwards nor mutate the
        returned live object."""
        self._check_kind(kind)
        if copy_obj:
            obj = copy.deepcopy(obj)
        with self._lock:
            key = _key(kind, obj)
            if key in self._objects[kind]:
                raise ConflictError(f"{kind} {key!r} already exists")
            self._touch(kind, key)
            md = obj.setdefault("metadata", {})
            if kind in NAMESPACED_KINDS:
                md.setdefault("namespace", "default")
            md["resourceVersion"] = str(next(self._rv))
            md.setdefault("uid", f"uid-{kind}-{md['resourceVersion']}")
            self._objects[kind][key] = obj
            bisect.insort(self._sorted_keys[kind], (name_of(obj), key))
            if kind == "pods":
                self._index_pod(key, obj)
            # The stored object is frozen (writes replace, never mutate), so
            # the event and history can share it without a copy.
            self._notify(WatchEvent(kind, ADDED, obj))
            return copy.deepcopy(obj) if copy_obj else obj

    def get(self, kind: str, name: str, namespace: str = "") -> JSON:
        self._check_kind(kind)
        with self._lock:
            key = _key(kind, name, namespace)
            try:
                return copy.deepcopy(self._objects[kind][key])
            except KeyError:
                raise NotFoundError(f"{kind} {key!r} not found") from None

    def contains(self, kind: str, name: str, namespace: str = "") -> bool:
        """Keyed membership probe — no deep copy, no NotFoundError (the
        replay lowering's deferred store-membership checks run one probe
        per window event on the hot cache-hit path)."""
        self._check_kind(kind)
        with self._lock:
            return _key(kind, name, namespace) in self._objects[kind]

    def list(self, kind: str, namespace: str = "", *, copy_objs: bool = True) -> list[JSON]:
        """List objects sorted by name.  ``copy_objs=False`` returns the
        live dicts for READ-ONLY hot paths (featurization lists the whole
        cluster every scheduling pass; deep-copying thousands of pod dicts
        per pass dominated churn-replay profiles) — callers must not
        mutate and must not hold them across store writes."""
        self._check_kind(kind)
        with self._lock:
            table = self._objects[kind]
            out = [table[k] for _, k in self._sorted_keys[kind]]
            if namespace and kind in NAMESPACED_KINDS:
                out = [o for o in out if namespace_of(o) == namespace]
            return copy.deepcopy(out) if copy_objs else out

    def update(
        self,
        kind: str,
        obj: JSON,
        *,
        expect_rv: str | None = None,
        copy_obj: bool = True,
    ) -> JSON:
        """Replace an object; raises ConflictError if expect_rv is stale.
        ``copy_obj=False``: same ownership-transfer contract as
        ``create``."""
        self._check_kind(kind)
        if copy_obj:
            obj = copy.deepcopy(obj)
        with self._lock:
            key = _key(kind, obj)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key!r} not found")
            if expect_rv is not None and current["metadata"]["resourceVersion"] != expect_rv:
                raise ConflictError(
                    f"{kind} {key!r}: resourceVersion {expect_rv} is stale"
                )
            self._touch(kind, key)
            md = obj.setdefault("metadata", {})
            if kind in NAMESPACED_KINDS:
                md.setdefault("namespace", "default")
            md["uid"] = current["metadata"].get("uid")
            md["resourceVersion"] = str(next(self._rv))
            self._objects[kind][key] = obj
            if kind == "pods":
                self._index_pod(key, obj)
            self._notify(WatchEvent(kind, MODIFIED, obj))
            return copy.deepcopy(obj) if copy_obj else obj

    def patch(
        self,
        kind: str,
        name: str,
        namespace: str,
        mutate: Callable[[JSON], None],
        *,
        copy_ret: bool = True,
    ) -> JSON:
        """Atomic read-modify-write under the store lock.
        ``copy_ret=False`` returns the stored live object (read-only
        contract) — for bulk writers that discard the result."""
        self._check_kind(kind)
        with self._lock:
            key = _key(kind, name, namespace)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key!r} not found")
            obj = copy.deepcopy(current)
            mutate(obj)
            self._touch(kind, key)
            obj["metadata"]["resourceVersion"] = str(next(self._rv))
            self._objects[kind][key] = obj
            if kind == "pods":
                self._index_pod(key, obj)
            self._notify(WatchEvent(kind, MODIFIED, obj))
            return copy.deepcopy(obj) if copy_ret else obj

    def rewrap(
        self, kind: str, name: str, namespace: str, build: Callable[[JSON], JSON]
    ) -> JSON:
        """Atomic replace from a shallow re-wrap: ``build(current)``
        returns a NEW top-level object that may SHARE unmodified
        substructures with ``current`` (which is frozen — writes replace,
        never mutate).  This skips the full deepcopy ``patch`` pays,
        which matters on the scheduler's bind path: pods accumulate
        megabytes of result-history annotations and deep-copying them on
        every attempt dominated the record="full" product path.

        Contract: ``build`` must not mutate ``current`` or any shared
        substructure, must return a fresh ``metadata`` dict (it gets the
        new resourceVersion), and the returned object is stored AND
        shared with watch events — the caller must treat it as frozen.
        """
        self._check_kind(kind)
        with self._lock:
            key = _key(kind, name, namespace)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key!r} not found")
            obj = build(current)
            self._touch(kind, key)
            md = obj["metadata"] = dict(obj.get("metadata") or {})
            md["resourceVersion"] = str(next(self._rv))
            self._objects[kind][key] = obj
            if kind == "pods":
                self._index_pod(key, obj)
            self._notify(WatchEvent(kind, MODIFIED, obj))
            return obj

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        self._check_kind(kind)
        with self._lock:
            key = _key(kind, name, namespace)
            if key in self._objects[kind]:
                self._touch(kind, key)
            obj = self._objects[kind].pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind} {key!r} not found")
            if kind == "pods":
                self._index_pod(key, None)
            entry = (name_of(obj), key)
            idx = bisect.bisect_left(self._sorted_keys[kind], entry)
            sk = self._sorted_keys[kind]
            if idx < len(sk) and sk[idx] == entry:
                del sk[idx]
            # A delete is a new store event: stamp a fresh resourceVersion
            # (like the apiserver) so watch-resume replay — which filters
            # history on rv > lastResourceVersion — never drops it.  The
            # rebumped object is a shallow re-wrap: the popped dict may be
            # shared with earlier events/history (frozen contract) and
            # must not be mutated in place.
            obj = dict(obj, metadata=dict(obj["metadata"], resourceVersion=str(next(self._rv))))
            self._notify(WatchEvent(kind, DELETED, obj))

    def apply(self, kind: str, obj: JSON) -> JSON:
        """Create-or-update (the reference Load path uses server-side apply,
        simulator/snapshot/snapshot.go:158-196)."""
        self._check_kind(kind)
        with self._lock:
            key = _key(kind, obj)
            if key in self._objects[kind]:
                return self.update(kind, obj)
            return self.create(kind, obj)

    # -- watch --------------------------------------------------------------

    def watch(
        self,
        kinds: tuple[str, ...] = KINDS,
        *,
        since: dict[str, int] | None = None,
        list_first: tuple[str, ...] = (),
    ) -> "WatchStream":
        """Subscribe to events for ``kinds``.

        ``since`` maps kind -> lastResourceVersion: events after that
        version replay from the bounded history buffer first (the
        reference's RetryWatcher resume, resourcewatcher.go:128-134); a
        version older than the buffer raises ExpiredError — the etcd
        compaction "410 Gone" — telling the client to drop its cache and
        relist (a silent relist could never signal deletions it missed).
        ``list_first`` kinds get their current objects as ADDED events
        (the reference's list-then-watch when no lastResourceVersion is
        given, eventproxy.go:66-80).  Everything happens under one lock,
        so replay/list and the live subscription have no event gap."""
        for k in kinds:
            self._check_kind(k)
        q: queue.SimpleQueue = queue.SimpleQueue()
        with self._lock:
            if since and not self._history:
                # A resume point against a store that never emitted an
                # event can only come from a PREVIOUS store life (server
                # restart): it cannot be verified, so answer Gone and let
                # the client drop its cache and relist — silently
                # accepting it would leave the client showing pre-restart
                # objects forever.
                for kind, last in since.items():
                    self._check_kind(kind)
                    if kind in kinds and last > 0:
                        raise ExpiredError(
                            f"{kind} resourceVersion {last} predates this "
                            "store (no event history)"
                        )
            if since and self._history:
                covered_from = self._history[0][0]
                covered_to = self._history[-1][0]
                for kind, last in since.items():
                    self._check_kind(kind)
                    if kind not in kinds:
                        continue
                    if last + 1 < covered_from:
                        raise ExpiredError(
                            f"{kind} resourceVersion {last} is too old "
                            f"(history starts at {covered_from})"
                        )
                    if last > covered_to:
                        # From a previous store life whose rv counter ran
                        # ahead of this one — unverifiable, same as above.
                        raise ExpiredError(
                            f"{kind} resourceVersion {last} is ahead of "
                            f"this store (history ends at {covered_to})"
                        )
            for kind in list_first:
                self._check_kind(kind)
                for obj in self._objects[kind].values():
                    q.put(WatchEvent(kind, ADDED, copy.deepcopy(obj)))
            if since and self._history:
                for kind, last in since.items():
                    if kind not in kinds:
                        continue
                    for rv, ev in self._history:
                        if ev.kind == kind and rv > last:
                            q.put(ev)
            self._watchers.append((q, frozenset(kinds)))
        return WatchStream(self, q)

    def _unwatch(self, q: queue.SimpleQueue) -> None:
        with self._lock:
            self._watchers = [(w, ks) for (w, ks) in self._watchers if w is not q]

    def _notify(self, event: WatchEvent) -> None:  # ksimlint: lock-held(_lock)
        self._assert_owned()
        txn = self._txn
        if txn is not None:
            if not txn.epoch_exempt:
                self._mutation_epoch += 1
            # Staged: delivery (history + watcher queues) happens at
            # commit, in write order; rollback drops the event unseen.
            txn.events.append(event)
            return
        self._mutation_epoch += 1
        self._deliver(event)

    def _deliver(self, event: WatchEvent) -> None:  # ksimlint: lock-held(_lock)
        self._assert_owned()
        try:
            rv = int(event.obj["metadata"]["resourceVersion"])
        except (KeyError, ValueError, TypeError):
            rv = 0
        self._history.append((rv, event))
        for q, kinds in self._watchers:
            if event.kind in kinds:
                q.put(event)

    # -- snapshot/restore (reset service substrate) -------------------------

    def dump(self) -> dict[str, dict[str, JSON]]:
        with self._lock:
            return copy.deepcopy(self._objects)

    def restore(self, dump: dict[str, dict[str, JSON]]) -> None:
        """Wipe and restore; emits DELETED then ADDED events
        (reference reset deletes the etcd prefix then re-puts initial KVs,
        simulator/reset/reset.go:58-85).  Every emitted event — and every
        restored object — gets a FRESH resourceVersion so watch-resume
        replay (which filters on rv > lastResourceVersion) sees all of
        them; the restored objects' recorded rvs are superseded, like an
        etcd re-put bumping mod_revision."""
        with self._lock:
            if self._txn is not None:
                raise RuntimeError("restore() inside a store transaction")
            for kind in KINDS:
                for obj in list(self._objects[kind].values()):
                    # Shallow re-wrap, not in-place: the stored dict may be
                    # shared with earlier events/history (frozen contract).
                    obj = dict(obj, metadata=dict(obj["metadata"], resourceVersion=str(next(self._rv))))
                    self._notify(WatchEvent(kind, DELETED, obj))
                self._objects[kind].clear()
                self._sorted_keys[kind] = []
                if kind == "pods":
                    self._with_node.clear()
                    self._without_node.clear()
                    self._by_node.clear()
                    self._node_of.clear()
            for kind, objs in dump.items():
                self._check_kind(kind)
                for key, obj in objs.items():
                    restored = copy.deepcopy(obj)
                    restored.setdefault("metadata", {})["resourceVersion"] = str(
                        next(self._rv)
                    )
                    self._objects[kind][key] = restored
                    bisect.insort(self._sorted_keys[kind], (name_of(restored), key))
                    if kind == "pods":
                        self._index_pod(key, restored)
                    self._notify(WatchEvent(kind, ADDED, restored))

    # -- exact-state checkpoint (incremental job resume) --------------------

    def checkpoint(self) -> dict[str, Any]:
        """JSON-safe EXACT-state snapshot for the job plane's segment
        checkpoints (ksim_tpu/jobs/manager.py).

        Unlike ``dump``/``restore`` — which re-stamp fresh
        resourceVersions on load, like the reference reset service's
        etcd re-put (simulator/reset/reset.go:58-85) — a checkpoint
        carries the objects VERBATIM (rv and uid included) plus the rv
        counter position and the mutation epoch, so a restored store is
        byte-identical to the original: replaying the remaining event
        suffix consumes the same resourceVersions and mints the same
        ``uid-<kind>-<rv>`` defaults an uninterrupted run would have.
        Refused inside a transaction (a mid-segment snapshot would
        capture staged, uncommitted writes)."""
        with self._lock:
            if self._txn is not None:
                raise RuntimeError("checkpoint() inside a store transaction")
            # Peek the rv counter without consuming a version: next()
            # is the only read an itertools.count offers, so reinstall
            # a fresh count at the observed position.
            rv_next = next(self._rv)
            self._rv = itertools.count(rv_next)
            return {
                "objects": copy.deepcopy(self._objects),
                "rv_next": rv_next,
                "mutation_epoch": self._mutation_epoch,
            }

    @classmethod
    def from_checkpoint(
        cls, state: dict[str, Any], *, strict: "bool | None" = None
    ) -> "ClusterStore":
        """Reconstruct a store from a ``checkpoint()`` document.

        Objects install verbatim (no fresh rv/uid — the whole point),
        the rv counter resumes at the recorded position, the mutation
        epoch restores exactly (the replay lower-cache anchors plan
        validity on it — a restored store must not alias a cached
        epoch), and the incremental indexes (name-sorted keys, the pod
        nodeName partition) rebuild from the objects.  No watch events
        are emitted: the store is fresh, nothing subscribed yet."""
        store = cls(strict=strict)
        with store._lock:
            for kind, objs in state["objects"].items():
                store._check_kind(kind)
                table = store._objects[kind]
                sk = store._sorted_keys[kind]
                for key, obj in objs.items():
                    restored = copy.deepcopy(obj)
                    table[key] = restored
                    bisect.insort(sk, (name_of(restored), key))
                    if kind == "pods":
                        store._index_pod(key, restored)
            store._rv = itertools.count(int(state["rv_next"]))
            store._mutation_epoch = int(state["mutation_epoch"])
        return store

    def _check_kind(self, kind: str) -> None:
        # The KINDS key set of _objects is fixed at construction (only
        # the inner per-kind tables mutate), so this membership probe is
        # safe before the lock — public mutators call it on their way in.
        if kind not in self._objects:  # ksimlint: disable=lock-discipline
            raise NotFoundError(f"unknown kind {kind!r}")


class WatchStream:
    """Iterator over watch events; close() detaches from the store."""

    def __init__(self, store: ClusterStore, q: queue.SimpleQueue) -> None:
        self._store = store
        self._q = q
        self._closed = False

    def next(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self) -> Iterator[WatchEvent]:
        while not self._closed:
            ev = self.next(timeout=0.1)
            if ev is not None:
                yield ev

    def close(self) -> None:
        self._closed = True
        self._store._unwatch(self._q)
