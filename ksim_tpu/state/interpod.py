"""InterPodAffinity tensor encoding.

SURVEY.md hard part 3 — the O(P x N x existing-pods) pairwise pod-pod term
matching of InterPodAffinity (the capability the reference exercises through
its wrapped plugin calls, reference simulator/scheduler/plugin/
wrappedplugin.go:420-548; semantics re-derived from upstream kube-scheduler
v1.30 plugins/interpodaffinity/{filtering,scoring}.go).

The same host/device split as the other affinity-family encoders
(state/encoding.py):

- **Host side** (here): build vocabularies of distinct *match contexts*
  (namespaces + namespaceSelector + labelSelector — the part of an affinity
  term that matches *pods*) and *terms* (context x topologyKey).  Evaluate
  every bound and queue pod against every context once in exact Python.
- **Device side** (plugins/interpodaffinity.py): per-node domain-count
  tensors are the scan carry itself, so every per-pod check is a
  ``[N,T] x [T]`` matvec — vmapped over pods these become ``[P,T] x [T,N]``
  MXU matmuls.

Scan-carried state (so later queue pods see earlier placements) is kept in
NODE space with the domain aggregation PRE-APPLIED: ``cnt_node`` [N,T]
(pods matching term t's context anywhere in node n's t-domain),
``ecnt_node`` [N,T] (pods with required anti-affinity term t in n's
t-domain), ``ew_node`` [N,T] (signed score weight of existing pods' terms
in n's t-domain: required-affinity terms count HardPodAffinityWeight each,
preferred affinity +w, preferred anti-affinity -w — upstream scoring.go
processExistingPod), ``total`` [T] (cluster-wide matches on key-carrying
nodes, the first-pod-escape check).  Committing a pod to node b updates
all nodes sharing b's domain with an elementwise same-domain mask — no
gather, scatter, or segment reduction anywhere in the scan step (TPU
gathers cost ~50us inside a compiled loop; elementwise [N,T] ops are
effectively free).  The domain-space tables built here exist only to
initialize those carries host-side.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ksim_tpu.state.resources import JSON, labels_of, name_of, namespace_of
from ksim_tpu.state.selectors import match_label_selector

# Upstream interpodaffinity default args (scheduler.config defaults).
DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass
class InterPodTensors:
    """Vocab arrays for the InterPodAffinity kernels.

    Axes: N nodes (padded), P queue pods (padded), U distinct match
    contexts, T distinct (context, topologyKey) terms, TK distinct topology
    keys, Dom distinct (key, value) domains.
    """

    AXES = {
        "node_dom": "node",
        "dom_t": "node",
        "cnt_node": "node",
        "ecnt_node": "node",
        "ew_node": "node",
        "total": None,
        "term_u": None,
        "term_tk": None,
        "pod_ctx_match": "pod",
        "pod_term_match": "pod",
        "req_aff": "pod",
        "req_anti": "pod",
        "self_aff": "pod",
        "pref_w": "pod",
        "pod_vw": "pod",
        "pod_eat": "pod",
    }

    n_domains: int  # static Dom size
    hard_weight: int  # HardPodAffinityWeight folded into ew/pod_vw
    node_dom: np.ndarray  # i32 [N, TK] domain id or -1 (key absent)
    dom_t: np.ndarray  # i32 [N, T] == node_dom[:, term_tk] (per-term view)
    cnt_node: np.ndarray  # i32 [N, T] initial t-domain ctx matches per node
    ecnt_node: np.ndarray  # i32 [N, T] initial t-domain required-anti counts
    ew_node: np.ndarray  # i32 [N, T] initial t-domain signed score weight
    total: np.ndarray  # i32 [T] initial cluster-wide matches (escape check)
    term_u: np.ndarray  # i32 [T] term -> context id
    term_tk: np.ndarray  # i32 [T] term -> topology-key id
    pod_ctx_match: np.ndarray  # bool [P, U] queue pod matches ctx u
    pod_term_match: np.ndarray  # bool [P, T] == pod_ctx_match[:, term_u]
    req_aff: np.ndarray  # bool [P, T] pod's required affinity terms
    req_anti: np.ndarray  # bool [P, T] pod's required anti-affinity terms
    self_aff: np.ndarray  # bool [P] pod matches ALL its own required aff terms
    pref_w: np.ndarray  # i32 [P, T] incoming preferred weights (signed)
    pod_vw: np.ndarray  # i32 [P, T] pod's ew contribution when committed
    pod_eat: np.ndarray  # i32 [P, T] pod's ranti contribution when committed


class _Vocab:
    """Context and term id assignment with exact canonical keys."""

    def __init__(self) -> None:
        self.ctx_ids: dict[str, int] = {}
        self.ctxs: list[dict] = []
        self.term_ids: dict[tuple[int, int], int] = {}
        self.terms: list[tuple[int, int]] = []
        self.tk_ids: dict[str, int] = {}

    def ctx_id(self, ctx: dict) -> int:
        return self.ctx_id_by_key(
            _canon({"ns": ctx["namespaces"], "nsSel": ctx["ns_sel"], "sel": ctx["sel"]}),
            ctx,
        )

    def ctx_id_by_key(self, k: str, ctx: dict) -> int:
        if k not in self.ctx_ids:
            self.ctx_ids[k] = len(self.ctxs)
            self.ctxs.append(ctx)
        return self.ctx_ids[k]

    def tk_id(self, k: str) -> int:
        if k not in self.tk_ids:
            self.tk_ids[k] = len(self.tk_ids)
        return self.tk_ids[k]

    def term_id(self, u: int, tk: int) -> int:
        key = (u, tk)
        if key not in self.term_ids:
            self.term_ids[key] = len(self.terms)
            self.terms.append(key)
        return self.term_ids[key]


def term_context(term: JSON, owner_ns: str) -> dict:
    """An affinity term's pod-matching part (upstream framework
    AffinityTerm): explicit namespaces default to the DEFINING pod's
    namespace iff both namespaces and namespaceSelector are unset; a nil
    labelSelector matches NOTHING (metav1.LabelSelectorAsSelector(nil))
    while an empty one matches everything.  Memoized per term object so
    the returned dict is identity-stable across featurizations."""
    from ksim_tpu.state import objcache

    return objcache.cached("ipctx", term, lambda: _term_context(term, owner_ns), owner_ns)


def _term_context(term: JSON, owner_ns: str) -> dict:
    namespaces = sorted(term.get("namespaces") or [])
    ns_sel = term.get("namespaceSelector")
    if not namespaces and ns_sel is None:
        namespaces = [owner_ns]
    return {
        "namespaces": namespaces,
        "ns_sel": ns_sel,
        "sel": term.get("labelSelector"),
    }


def context_matches(ctx: dict, pod: JSON, ns_labels: dict[str, dict]) -> bool:
    """AffinityTerm.Matches(pod, nsLabels): namespace gate then selector."""
    ns = namespace_of(pod) or "default"
    in_ns = ns in ctx["namespaces"] or (
        ctx["ns_sel"] is not None
        and match_label_selector(ctx["ns_sel"], ns_labels.get(ns, {}))
    )
    if not in_ns:
        return False
    if ctx["sel"] is None:
        return False
    return match_label_selector(ctx["sel"], labels_of(pod))


def _pod_terms(pod: JSON) -> dict[str, list]:
    """Extract the four term families from a pod spec (memoized)."""
    from ksim_tpu.state import objcache

    def build() -> dict[str, list]:
        aff = (pod.get("spec", {}).get("affinity") or {})
        pa = aff.get("podAffinity") or {}
        paa = aff.get("podAntiAffinity") or {}
        return {
            "req_aff": list(pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []),
            "req_anti": list(paa.get("requiredDuringSchedulingIgnoredDuringExecution") or []),
            "pref_aff": list(pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []),
            "pref_anti": list(paa.get("preferredDuringSchedulingIgnoredDuringExecution") or []),
        }

    return objcache.cached("ipterms", pod, build)


def parsed_terms(pod: JSON) -> dict[str, list[tuple[dict, str, str, int]]]:
    """family -> [(ctx, canon_key, topologyKey, weight)] — everything
    about a pod's affinity terms that is independent of the per-call
    vocab, memoized per pod object so replay passes skip the JSON walk
    AND the canonical-key dumps."""
    from ksim_tpu.state import objcache

    def build() -> dict[str, list[tuple[dict, str, str, int]]]:
        owner_ns = namespace_of(pod) or "default"
        fams = _pod_terms(pod)
        out: dict[str, list[tuple[dict, str, str, int]]] = {}
        for fam in ("req_aff", "req_anti"):
            items = []
            for term in fams[fam]:
                ctx = term_context(term, owner_ns)
                ck = _canon({"ns": ctx["namespaces"], "nsSel": ctx["ns_sel"], "sel": ctx["sel"]})
                items.append((ctx, ck, term.get("topologyKey", ""), 1))
            out[fam] = items
        for fam in ("pref_aff", "pref_anti"):
            items = []
            for wt in fams[fam]:
                term = wt.get("podAffinityTerm") or {}
                ctx = objcache.cached(
                    "ipctx", wt, lambda t=term, ns=owner_ns: _term_context(t, ns), owner_ns
                )
                ck = _canon({"ns": ctx["namespaces"], "nsSel": ctx["ns_sel"], "sel": ctx["sel"]})
                items.append((ctx, ck, term.get("topologyKey", ""), int(wt.get("weight", 0))))
            out[fam] = items
        return out

    return objcache.cached("ipparsed", pod, build)


def has_any_affinity(pod: JSON) -> bool:
    """NodeInfo.PodsWithAffinity membership: any pod(Anti)Affinity stanza."""
    t = _pod_terms(pod)
    return any(t.values())


def encode_inter_pod(
    nodes: Sequence[JSON],
    pods: Sequence[JSON],
    bound_pods: Sequence[JSON],
    namespaces: Sequence[JSON],
    n_padded: int,
    p_padded: int,
    *,
    hard_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT,
    agg: dict | None = None,
    bound_map: "dict[int, JSON] | None" = None,
    changed_slots: "set[int] | None" = None,
    slot_of: "dict[str, int] | None" = None,
) -> InterPodTensors:
    """With ``agg`` (a persistent Featurizer's state, state/boundagg.py)
    the context/term/domain vocabularies persist append-only across
    calls — ids stay stable — and the existing-pod domain aggregates
    (match counts, required-anti counts, signed score weights) update by
    delta over the bound population.  The match aggregate rebuilds when
    the context vocabulary or namespace labels change (a new context can
    match pods that did not themselves change); the term aggregates only
    depend on each pod's own terms, so they survive vocabulary growth.
    Without ``agg``, one-shot rebuild with throwaway state (identical
    results)."""
    from ksim_tpu.state.boundagg import sync_family
    from ksim_tpu.state.featurizer import vocab_pad

    agg = agg if agg is not None else {}
    if bound_map is None:
        bound_map = {id(p): p for p in bound_pods}
    changed_slots = changed_slots if changed_slots is not None else set()

    # Persistent vocabularies, with a reset valve: adversarial streams
    # could grow them without bound (every reset is just one full
    # rebuild).
    vocab: _Vocab = agg.setdefault("ip_vocab", _Vocab())
    dom_vocab: dict[tuple[int, str], int] = agg.setdefault("ip_doms", {})
    if len(vocab.ctxs) > 4096 or len(vocab.terms) > 4096 or len(dom_vocab) > (1 << 17):
        for k in ("ip_vocab", "ip_doms", "ip_seen", "ip_match", "ip_terms"):
            agg.pop(k, None)
        vocab = agg.setdefault("ip_vocab", _Vocab())
        dom_vocab = agg.setdefault("ip_doms", {})
        # New vocabulary lineage: keys derived from dom_vocab content
        # (the cached node-domain tables below) must not alias entries
        # from the pre-reset lineage.
        agg["ip_doms_gen"] = agg.get("ip_doms_gen", 0) + 1

    ns_labels = {name_of(ns): dict(labels_of(ns)) for ns in namespaces}

    def terms_of(pod: JSON) -> dict[str, list[tuple[int, int, int]]]:
        """family -> [(term_id, ctx_id, weight)]"""
        out: dict[str, list[tuple[int, int, int]]] = {}
        for fam, items in parsed_terms(pod).items():
            mapped = []
            for ctx, ck, tk, w in items:
                u = vocab.ctx_id_by_key(ck, ctx)
                t = vocab.term_id(u, vocab.tk_id(tk))
                mapped.append((t, u, w))
            out[fam] = mapped
        return out

    # Registration pre-pass: every CURRENT pod's contexts/terms must be
    # in the vocab before any vocab-derived token or array is built.
    # Queue pods register every call (cheap, the queue is bounded);
    # bound pods register once (persistent ``ip_seen``).
    queue_terms = [terms_of(p) for p in pods]
    seen: set[int] = agg.setdefault("ip_seen", set())
    # In-place: ``seen &= dict.keys()`` would REBIND the local to a new
    # set and orphan the persisted one.
    seen.intersection_update(bound_map.keys())
    for pid, p in bound_map.items():
        if pid not in seen:
            terms_of(p)
            seen.add(pid)

    # Padded terms are inert: term_u/term_tk 0 with all-zero pod columns.
    U = vocab_pad(len(vocab.ctxs))
    T = vocab_pad(len(vocab.terms))
    TK = max(len(vocab.tk_ids), 1)

    term_u = np.zeros(T, dtype=np.int32)
    term_tk = np.zeros(T, dtype=np.int32)
    for ti, (u, tk) in enumerate(vocab.terms):
        term_u[ti] = u
        term_tk[ti] = tk

    # Topology domains from node labels (domain ids persist append-only,
    # so bound-pod contribution records stay valid across passes).
    from ksim_tpu.state import objcache

    def build_node_domains():
        node_dom = np.full((n_padded, TK), -1, dtype=np.int32)
        for ni, node in enumerate(nodes):
            lbls = labels_of(node)
            for k, ki in vocab.tk_ids.items():
                if k in lbls:
                    dk = (ki, lbls[k])
                    if dk not in dom_vocab:
                        dom_vocab[dk] = len(dom_vocab)
                    node_dom[ni, ki] = dom_vocab[dk]
        n_domains = max(len(dom_vocab), 1)
        D = vocab_pad(n_domains + 1)  # +1 keeps a write-only junk row
        dom_tk = np.full(D, -1, dtype=np.int32)
        for (ki, _val), d in dom_vocab.items():
            dom_tk[d] = ki
        return node_dom, n_domains, D, dom_tk

    # Family-cached on the exact node objects + tk vocab.  ``dom_vocab``
    # is persistent and append-only within a lineage (ip_doms_gen bumps
    # at the reset valve), so (lineage, size) pins its exact content: a
    # hit guarantees the same ids and dom_tk as at build time, and that
    # the build would register nothing new for these nodes.
    node_dom, n_domains, D, dom_tk = objcache.cached_seq(
        "enc_ip_nodes",
        nodes,
        build_node_domains,
        tuple(vocab.tk_ids),
        agg.get("ip_doms_gen", 0),
        len(dom_vocab),
        n_padded,
    )

    node_index = slot_of if slot_of is not None else {
        name_of(n): i for i, n in enumerate(nodes)
    }
    N0 = len(nodes)

    # Per-pod context-match rows, memoized on (pod object, final ctx
    # vocab, namespace labels): with a persistent vocab the token is
    # stable, so steady state is one dict lookup per pod.
    U0 = len(vocab.ctxs)
    vocab_token = objcache.intern_token(tuple(vocab.ctx_ids))
    ns_token = objcache.intern_token(_canon(ns_labels))

    def match_row(pod: JSON) -> np.ndarray:
        key = ("iprow", objcache.ref_id(pod), vocab_token, ns_token)
        hit = objcache.get(key)
        if hit is not objcache.MISS:
            return hit
        row = np.fromiter(
            (context_matches(ctx, pod, ns_labels) for ctx in vocab.ctxs),
            dtype=bool,
            count=U0,
        )
        return objcache.put(key, row)

    # Existing-pod state (the carry init), accumulated in domain space: a
    # bound pod on node ni contributes to ni's domain for EVERY topology
    # key (match counts) / for its term's topology key (term counts); a
    # node missing the key contributes nowhere (no topologyPair exists —
    # upstream filtering.go only counts nodes that carry the key).

    def _match_record(bp: JSON):
        ni = node_index.get(bp.get("spec", {}).get("nodeName", ""))
        if ni is None or ni >= N0:
            return None
        doms = [int(d) for d in node_dom[ni] if d >= 0]
        row = match_row(bp)
        uis = [int(ui) for ui in np.nonzero(row)[0]]
        if not doms or not uis:
            return (ni, ())
        return (ni, tuple((d, ui) for ui in uis for d in doms))

    def _match_apply(arr, rec, sign: int) -> None:
        for d, ui in rec[1]:
            arr[d, ui] += sign

    match_dom = sync_family(
        agg,
        "ip_match",
        (D, U, U0, len(vocab.tk_ids), ns_token, n_padded),
        bound_map,
        changed_slots,
        make_arrays=lambda: np.zeros((D, U), dtype=np.int32),
        record_of=_match_record,
        apply=_match_apply,
    )

    def _terms_record(bp: JSON):
        ni = node_index.get(bp.get("spec", {}).get("nodeName", ""))
        if ni is None or ni >= N0:
            return None
        terms = terms_of(bp)
        doms = node_dom[ni]
        entries = []  # (d, t, ranti_delta, ew_delta)
        for t, _u, _w in terms["req_anti"]:
            d = doms[term_tk[t]]
            if d >= 0:
                entries.append((int(d), t, 1, 0))
        for t, _u, _w in terms["req_aff"]:
            d = doms[term_tk[t]]
            if d >= 0:
                entries.append((int(d), t, 0, hard_weight))
        for t, _u, w in terms["pref_aff"]:
            d = doms[term_tk[t]]
            if d >= 0:
                entries.append((int(d), t, 0, w))
        for t, _u, w in terms["pref_anti"]:
            d = doms[term_tk[t]]
            if d >= 0:
                entries.append((int(d), t, 0, -w))
        return (ni, tuple(entries))

    def _terms_apply(arrays, rec, sign: int) -> None:
        ranti, ew = arrays
        for d, t, dr, dw in rec[1]:
            if dr:
                ranti[d, t] += sign * dr
            if dw:
                ew[d, t] += sign * dw

    ranti_dom, ew_dom = sync_family(
        agg,
        "ip_terms",
        (D, T, hard_weight, n_padded),
        bound_map,
        changed_slots,
        make_arrays=lambda: (
            np.zeros((D, T), dtype=np.int32),
            np.zeros((D, T), dtype=np.int32),
        ),
        record_of=_terms_record,
        apply=_terms_apply,
    )

    # Queue-pod tables.
    pod_ctx_match = np.zeros((p_padded, U), dtype=bool)
    req_aff = np.zeros((p_padded, T), dtype=bool)
    req_anti = np.zeros((p_padded, T), dtype=bool)
    self_aff = np.zeros(p_padded, dtype=bool)
    pref_w = np.zeros((p_padded, T), dtype=np.int32)
    pod_vw = np.zeros((p_padded, T), dtype=np.int32)
    pod_eat = np.zeros((p_padded, T), dtype=np.int32)
    for j, (pod, terms) in enumerate(zip(pods, queue_terms)):
        row = match_row(pod)
        pod_ctx_match[j, :U0] = row
        self_ok = True
        for t, u, _w in terms["req_aff"]:
            req_aff[j, t] = True
            pod_vw[j, t] += hard_weight
            self_ok = self_ok and bool(row[u])
        self_aff[j] = self_ok and bool(terms["req_aff"])
        for t, _u, _w in terms["req_anti"]:
            req_anti[j, t] = True
            pod_eat[j, t] += 1
        for t, _u, w in terms["pref_aff"]:
            pref_w[j, t] += w
            pod_vw[j, t] += w
        for t, _u, w in terms["pref_anti"]:
            pref_w[j, t] -= w
            pod_vw[j, t] -= w

    # Node-space carry initialization: pre-apply the domain aggregation so
    # the device never has to (see module docstring).
    dom_t = node_dom[:, term_tk]  # [N, T]
    safe = np.maximum(dom_t, 0)
    t_cols = np.arange(T)[None, :]
    cnt_node = np.where(dom_t >= 0, match_dom[safe, term_u[None, :]], 0).astype(np.int32)
    ecnt_node = np.where(dom_t >= 0, ranti_dom[safe, t_cols], 0).astype(np.int32)
    ew_node = np.where(dom_t >= 0, ew_dom[safe, t_cols], 0).astype(np.int32)
    total = np.array(
        [match_dom[dom_tk == term_tk[t], term_u[t]].sum() for t in range(T)],
        dtype=np.int32,
    )

    return InterPodTensors(
        n_domains=n_domains,
        hard_weight=hard_weight,
        node_dom=node_dom,
        dom_t=dom_t,
        cnt_node=cnt_node,
        ecnt_node=ecnt_node,
        ew_node=ew_node,
        total=total,
        term_u=term_u,
        term_tk=term_tk,
        pod_ctx_match=pod_ctx_match,
        pod_term_match=pod_ctx_match[:, term_u],
        req_aff=req_aff,
        req_anti=req_anti,
        self_aff=self_aff,
        pref_w=pref_w,
        pod_vw=pod_vw,
        pod_eat=pod_eat,
    )
