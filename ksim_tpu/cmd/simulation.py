"""One-shot SchedulerSimulation entrypoint (the reference's KEP-184
scenario-runner container: read a Scenario from a file, run it in a
simulator built from the spec, store the result to a file).

Run: ``python -m ksim_tpu.cmd.simulation sim.yaml [--result out.json]``.
Exit code 0 on Succeeded, 1 on Failed.
"""

from __future__ import annotations

import argparse
import json
import sys


def run_simulation(argv: "list[str] | None" = None) -> int:
    from ksim_tpu.util import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser(prog="ksim-simulation")
    ap.add_argument("document", help="SchedulerSimulation YAML/JSON document")
    ap.add_argument(
        "--result", default=None, help="override spec.scenarioResultFilePath"
    )
    args = ap.parse_args(argv)

    import yaml

    from ksim_tpu.scenario.simulation import run_scheduler_simulation

    with open(args.document) as f:
        doc = yaml.safe_load(f)
    if args.result:
        doc.setdefault("spec", {})["scenarioResultFilePath"] = args.result
    out = run_scheduler_simulation(doc)
    status = out.get("status", {})
    json.dump(status, sys.stdout, indent=1)
    print()
    return 0 if status.get("phase") == "Succeeded" else 1


def main() -> None:
    raise SystemExit(run_simulation())


if __name__ == "__main__":
    main()
