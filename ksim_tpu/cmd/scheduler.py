"""Debuggable-scheduler entrypoint (reference simulator/cmd/scheduler/
scheduler.go:17-28 + pkg/debuggablescheduler NewSchedulerCommand): run the
batch-evaluating scheduler standalone over a snapshot, printing the
recorded results — the library analogue of pointing the scheduler binary
at a cluster with ``--config``.

Run: ``python -m ksim_tpu.cmd.scheduler --snapshot snap.json
[--config scheduler.yaml] [--watch]`` (or the ``ksim-scheduler`` script).
Out-of-tree plugins register through
ksim_tpu.scheduler.profile.Builder registries in library use (the
WithPlugin analogue)."""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading

logger = logging.getLogger(__name__)


def run_scheduler(argv: list[str] | None = None) -> int:
    from ksim_tpu.util import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser(prog="ksim-scheduler")
    ap.add_argument("--snapshot", required=True, help="reference-format snapshot JSON")
    ap.add_argument("--config", default=None, help="KubeSchedulerConfiguration yaml")
    ap.add_argument(
        "--watch",
        action="store_true",
        help="keep running and schedule on cluster events (default: one pass)",
    )
    ap.add_argument("--out", default="-", help="write the result snapshot here")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )

    import yaml

    from ksim_tpu.scheduler.service import SchedulerService
    from ksim_tpu.state.cluster import ClusterStore
    from ksim_tpu.state.snapshot import SnapshotService

    sched_cfg = None
    if args.config:
        with open(args.config) as f:
            sched_cfg = yaml.safe_load(f) or {}

    store = ClusterStore()
    service = SchedulerService(store, config=sched_cfg)
    snap = SnapshotService(store, scheduler_service=service)
    with open(args.snapshot) as f:
        snap.load(json.load(f), ignore_scheduler_configuration=args.config is not None)

    if args.watch:
        service.start()
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        service.stop(timeout=None)  # process exit: join the loop for real
    else:
        placements = service.schedule_pending()
        scheduled = sum(1 for v in placements.values() if v)
        logger.info(
            "scheduled %d/%d pods", scheduled, len(placements)
        )
    out = snap.export_json()
    if args.out == "-":
        print(out)
    else:
        with open(args.out, "w") as f:
            f.write(out)
    return 0


def main() -> None:
    sys.exit(run_scheduler())


if __name__ == "__main__":
    main()
