"""Process entrypoints (reference simulator/cmd/{simulator,scheduler})."""
