"""Simulator server entrypoint (reference simulator/cmd/simulator/
simulator.go:35-136): load config, wire the DI container, optionally
one-shot-import or continuously sync an external snapshot source, start
the scheduler watch loop and the HTTP server, then wait for SIGTERM.

Run: ``python -m ksim_tpu.cmd.simulator [--config config.yaml]`` (or the
``ksim-simulator`` console script)."""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading

logger = logging.getLogger(__name__)


def start_simulator(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="ksim-simulator")
    ap.add_argument("--config", default=None, help="SimulatorConfiguration yaml")
    ap.add_argument("--port", type=int, default=None, help="override the port")
    ap.add_argument("--host", default=None, help="bind address (0.0.0.0 for containers)")
    ap.add_argument(
        "--profile-dir",
        default=None,
        help="write a jax.profiler trace (TensorBoard format) of the "
        "scheduling passes to this directory",
    )
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )

    from ksim_tpu.util import enable_compilation_cache

    enable_compilation_cache()
    from ksim_tpu.config import load_config
    from ksim_tpu.oneshotimporter import OneShotImporter
    from ksim_tpu.server import DIContainer, SimulatorServer
    from ksim_tpu.state.cluster import ClusterStore
    from ksim_tpu.state.snapshot import SnapshotService
    from ksim_tpu.syncer import Syncer

    cfg = load_config(args.config)
    if args.port is not None:
        cfg.port = args.port
    if args.host is not None:
        cfg.host = args.host

    di = DIContainer(
        scheduler_config=cfg.initial_scheduler_cfg,
        scheduler_config_path=cfg.kube_scheduler_config_path or None,
    )

    syncer = None
    kube_source = None
    if cfg.external_import_enabled or cfg.resource_sync_enabled:
        if cfg.kube_config:
            # Live kube-apiserver source (reference cmd/simulator/
            # simulator.go:59-71 builds external clients from kubeConfig).
            from ksim_tpu.syncer.kubeapi import KubeApiSource

            kube_source = KubeApiSource.from_kubeconfig(cfg.kube_config)
            export_side: object = kube_source
            sync_source: object = kube_source
        else:
            # Static snapshot-file source.
            with open(cfg.external_snapshot_path) as f:
                snap_data = json.load(f)
            file_store = ClusterStore()
            SnapshotService(file_store).load(snap_data, ignore_err=True)
            export_side = SnapshotService(file_store)
            sync_source = file_store
        if cfg.external_import_enabled:
            OneShotImporter(di.snapshot_service, export_side).import_cluster_resources(
                cfg.resource_import_label_selector
            )
        else:
            syncer = Syncer(sync_source, di.store).run()

    writeback = None
    from ksim_tpu.syncer.writeback import LiveWriteBack, writeback_enabled

    if writeback_enabled():
        if kube_source is not None and syncer is not None:
            # Opt-in live scheduling: push binds + result annotations back
            # to the real cluster (the reference's debuggable-scheduler
            # promise, docs/debuggable-scheduler.md:64).
            writeback = LiveWriteBack(kube_source, di.store).start()
            di.scheduler_service.add_eviction_listener(writeback.note_eviction)
            logger.info("live write-back enabled (KSIM_ALLOW_LIVE_WRITEBACK=1)")
        else:
            # Continuous sync only: one-shot import leaves a frozen
            # snapshot, and binding a live cluster from stale state would
            # race every real controller on it.  Say so loudly — a user
            # who set the flag would otherwise only learn from the
            # cluster staying untouched.
            logger.warning(
                "KSIM_ALLOW_LIVE_WRITEBACK=1 ignored: write-back needs "
                "continuous kube sync (resourceSyncEnabled + kubeConfig), "
                "not one-shot import or a snapshot file"
            )

    prewarm_mode = os.environ.get("KSIM_AOT_PREWARM")
    if prewarm_mode in ("1", "2"):
        # Load-only AOT warm start: deserialize the shape-ladder rungs
        # already on disk so the first tenant dispatch of each skips
        # the deserialize round (engine/replay.py prewarm_aot_cache —
        # it never cold-compiles; the persistent XLA compilation cache
        # enabled above covers the compile half).  Mode 2 keeps
        # rescanning (prewarm_rescan_loop) so executables OTHER fleet
        # workers store after our startup — including ladder rungs this
        # process never dispatched — load speculatively too.  Daemon
        # thread: a wedged chip tunnel inside jax device init must
        # never block server startup — the dispatch-path watchdog owns
        # that risk.
        from ksim_tpu.engine.replay import prewarm_aot_cache, prewarm_rescan_loop

        threading.Thread(
            target=prewarm_rescan_loop if prewarm_mode == "2" else prewarm_aot_cache,
            name="aot-prewarm",
            daemon=True,
        ).start()

    if args.profile_dir:
        di.scheduler_service.start_profiling(args.profile_dir)
    di.scheduler_service.start()
    server = SimulatorServer(
        di,
        host=cfg.host,
        port=cfg.port,
        cors_allowed_origins=cfg.cors_allowed_origin_list,
    ).start()
    logger.info("simulator server started on :%d", server.port)

    stop = threading.Event()

    def on_signal(signum, frame):
        logger.info("signal %s: shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    try:
        stop.wait()
    finally:
        server.shutdown_server()
        di.scheduler_service.stop_profiling()
        if writeback is not None:
            writeback.stop()
        if syncer is not None:
            syncer.stop()
        if kube_source is not None:
            kube_source.close()
        di.shutdown(timeout=None)  # process exit: join the loop for real
    return 0


def main() -> None:
    sys.exit(start_simulator())


if __name__ == "__main__":
    main()
