"""ksim_tpu — a TPU-native Kubernetes scheduler simulator.

A re-imagining of kubernetes-sigs/kube-scheduler-simulator (reference at
/root/reference, see SURVEY.md): the debuggable scheduler's per-(pod, node,
plugin) Filter/Score hot loop (reference:
simulator/scheduler/plugin/wrappedplugin.go:420-548) is collapsed into fused
JAX kernels evaluating all pod-by-node filter masks and score matrices in one
vmap/pjit pass on TPU, while preserving the reference's product surface:

- per-plugin, per-node scheduling results recorded as explainable annotations
  (reference: simulator/scheduler/plugin/resultstore/store.go)
- snapshot export/import with a JSON schema compatible with the reference's
  ``ResourcesForSnap`` (reference: simulator/snapshot/snapshot.go:33-54)
- KubeSchedulerConfiguration-driven profiles ("profile compilation" replaces
  the reference's Docker-restart reload, simulator/scheduler/scheduler.go:58-111)
- scenario replay (reference design: keps/140-scenario-based-simulation)
- a watchable REST/SSE API (reference: simulator/server/server.go:41-54)

Layout (maps to SURVEY.md section 7):
    state/     cluster state, quantities, snapshot JSON, featurizer
    plugins/   per-plugin kernel pairs (filter/score), numpy parity models
    engine/    batched evaluation, lax.scan commit loop, sharding
    sched/     scheduling framework: registry, wrapped plugins, result store
    server/    REST + SSE simulator shell
    services/  reset / syncer / importer / resource watcher
    scenario/  replay harness
"""

__version__ = "0.1.0"
