"""ksim_tpu — a TPU-native Kubernetes scheduler simulator.

A re-imagining of kubernetes-sigs/kube-scheduler-simulator (reference at
/root/reference, see SURVEY.md): the debuggable scheduler's per-(pod, node,
plugin) Filter/Score hot loop (reference:
simulator/scheduler/plugin/wrappedplugin.go:420-548) is collapsed into fused
JAX kernels evaluating all pod-by-node filter masks and score matrices in one
vmap/lax.scan pass on TPU, while preserving the reference's product surface:

- per-plugin, per-node scheduling results recorded as explainable annotations
  (reference: simulator/scheduler/plugin/resultstore/store.go)
- snapshot export/import with a JSON schema compatible with the reference's
  ``ResourcesForSnap`` (reference: simulator/snapshot/snapshot.go:33-54)
- KubeSchedulerConfiguration-driven profiles ("profile compilation" replaces
  the reference's Docker-restart reload, simulator/scheduler/scheduler.go:58-111)
- preemption, extender webhooks, resource syncing, scenario replay
  (reference design: keps/140-scenario-based-simulation)
- a watchable REST/streaming API + built-in UI (reference:
  simulator/server/server.go:41-54, web/)

Layout (maps to SURVEY.md section 7):
    state/     cluster store, quantities, snapshot JSON, featurizer, encoders
    plugins/   per-plugin kernels (filter/score), parity oracle, samples
    engine/    batched evaluation, lax.scan commit loop, sharding, annotations
    scheduler/ service, profiles, preemption, extenders
    server/    REST + streaming-watch shell, DI container, reset, UI
    syncer/    continuous cluster mirroring; oneshotimporter for boot import
    scenario/  replay harness (churn generator)
    cmd/       ksim-simulator / ksim-scheduler entrypoints

See docs/migration.md for the reference -> ksim_tpu capability map.
"""

__version__ = "0.2.0"
