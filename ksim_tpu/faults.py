"""Deterministic fault injection: named sites, armed with schedules.

The reference simulator has no fault injection anywhere (SURVEY.md §5);
its recovery story is retries + rollback, and nothing exercises them.
This module is the repo's single fault plane: production code declares
NAMED INJECTION SITES (one ``FAULTS.check("layer.site")`` call on the
failure-prone path) and tests — or an operator via the ``KSIM_FAULTS``
environment spec — ARM those sites with deterministic schedules.  An
unarmed site costs one dict lookup on an empty dict; nothing else.

Sites currently wired (see docs/faults.md for the full table):

- ``replay.lower``      segment lowering (engine/replay.py)
- ``replay.prelower``   the NEXT window's speculative store-independent
                        prefix, overlapped with the in-flight dispatch
                        (a fault here degrades that window's overlap
                        only — it re-parses synchronously)
- ``replay.dispatch``   per-segment device dispatch (under the watchdog)
- ``replay.reconcile``  per-step segment reconcile (inside the store
                        transaction — a fault here must roll back)
- ``service.schedule``  the scheduling pass (scheduler/service.py)
- ``writeback.push``    live-cluster write-back push (syncer/writeback.py)
- ``kubeapi.request``   any kube-apiserver HTTP request (syncer/kubeapi.py)
- ``jobs.run``          a tenant job starting on a job-plane worker
                        (ksim_tpu/jobs/manager.py; a fault here fails
                        that one job, never the worker pool)
- ``jobs.lease_claim``  a fleet member claiming a job lease
                        (ksim_tpu/jobs/fleet.py; a fault here skips ONE
                        claim attempt — another member, or the next
                        poll, picks the job up)
- ``jobs.lease_renew``  a fleet worker's heartbeat renewal batch (a
                        fault here is survivable until lease expiry)

Schedules are deterministic by construction — "fail call N" and "fail
the first K calls" count per-site calls, "hang" sleeps (simulating a
wedged backend; the caller's watchdog is what's under test), and the
probabilistic schedule draws from a per-site seeded RNG so a failing
run replays exactly.

Spec string (``KSIM_FAULTS`` or ``FaultPlane.configure``): comma- or
semicolon-separated ``site=schedule[@error]`` entries::

    KSIM_FAULTS="replay.dispatch=always,writeback.push=first:2"

    call:N        fail exactly the Nth call (1-based)
    first:K       fail calls 1..K
    always        fail every call
    p:P[:SEED]    fail each call with probability P (seeded, default 0)
    hang:T[:K]    sleep T seconds on every call (or only the first K),
                  then CONTINUE — pairs with a caller-side watchdog

``@error`` picks the exception class from ``ERROR_REGISTRY`` (default
``fault`` = InjectedFault, a SimulatorError — classified layers treat it
as an expected, containable failure).  ``@type`` raises a TypeError: a
planted PROGRAMMING error, which classified handlers must re-raise
rather than absorb (tests/test_replay_faults.py pins that).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass

from ksim_tpu.errors import (
    DeviceUnavailableError,
    ReplayFallback,
    SimulatorError,
)
from ksim_tpu.obs import TRACE

logger = logging.getLogger(__name__)

#: Every wired injection site, in pipeline order.  This is the ONE
#: machine-readable list (the docstring table above is prose): each site
#: fires inside the trace-plane span of the same name (obs.SPAN_NAMES),
#: and tests/test_obs.py's registry-sync test asserts this tuple matches
#: the ``FAULTS.check("...")`` call sites in the source AND stays
#: covered by the span taxonomy — the two registries cannot drift apart
#: silently.
SITES: tuple[str, ...] = (
    "replay.lower",
    "replay.prelower",
    "replay.dispatch",
    "replay.reconcile",
    "service.schedule",
    "writeback.push",
    "kubeapi.request",
    "jobs.run",
    "jobs.journal_append",
    "jobs.journal_replay",
    "jobs.checkpoint_append",
    "jobs.checkpoint_restore",
    "jobs.lease_claim",
    "jobs.lease_renew",
    "traces.stream",
)


class InjectedFault(SimulatorError):
    """The fault plane's default injected error — a SimulatorError, so
    every classified handler treats it as an expected fault."""


#: ``@name`` suffixes in a spec string -> exception class.  ``type`` is
#: deliberately a non-SimulatorError: it plants a programming error that
#: classified handlers must RE-RAISE, not absorb.
ERROR_REGISTRY: dict[str, type[BaseException]] = {
    "fault": InjectedFault,
    "device": DeviceUnavailableError,
    "fallback": ReplayFallback,
    "simerr": SimulatorError,
    "runtime": RuntimeError,
    "oserror": OSError,
    "type": TypeError,
}


@dataclass
class _Armed:
    """One armed site: schedule kind + parameters + counters."""

    kind: str  # call | first | always | p | hang
    n: int = 0  # call:N / first:K / hang's K (0 = every call)
    prob: float = 0.0
    hang_s: float = 0.0
    exc: type[BaseException] = InjectedFault
    rng: random.Random | None = None
    calls: int = 0  # per-arming; the durable counters live in SiteStats

    def should_fire(self) -> bool:
        if self.kind == "always":
            return True
        if self.kind == "call":
            return self.calls == self.n
        if self.kind == "first":
            return self.calls <= self.n
        if self.kind == "hang":
            return self.n == 0 or self.calls <= self.n
        if self.kind == "p":
            return self.rng.random() < self.prob
        return False


@dataclass
class SiteStats:
    calls: int = 0
    fired: int = 0


class FaultPlane:
    """Process-global registry of armed injection sites.

    Thread-safe: sites are hit from the scheduler watch loop, the
    write-back thread, and the replay dispatch worker concurrently.
    The hang schedule sleeps OUTSIDE the lock so a hanging site never
    wedges the whole plane.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sites: dict[str, _Armed] = {}  # guarded-by: _lock
        # Counters survive disarm/reset-armed so a test can assert the
        # fault was exercised after the run completed and cleaned up.
        self._stats: dict[str, SiteStats] = {}  # guarded-by: _lock

    # -- arming ----------------------------------------------------------

    def arm(
        self,
        site: str,
        schedule: str = "always",
        *,
        exc: "type[BaseException] | None" = None,
    ) -> None:
        """Arm ``site`` with a schedule string (the spec grammar's
        right-hand side, e.g. ``"call:3"``, ``"hang:2:1"``,
        ``"first:2@device"``).  ``exc`` overrides the error class (wins
        over an ``@name`` suffix) — tests use it to plant exception
        types outside the registry."""
        entry = self._parse(site, schedule)
        if exc is not None:
            entry.exc = exc
        with self._lock:
            self._sites[site] = entry
            self._stats.setdefault(site, SiteStats())

    def disarm(self, site: "str | None" = None) -> None:
        """Disarm one site (or all).  Exercised-fault counters persist
        until ``reset``."""
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)

    def reset(self) -> None:
        """Disarm everything and clear all counters (test teardown)."""
        with self._lock:
            self._sites.clear()
            self._stats.clear()

    def configure(self, spec: str) -> None:
        """Parse a ``KSIM_FAULTS`` spec string and arm every entry.
        Malformed entries raise ValueError (a silently ignored fault
        spec would make a chaos run vacuously green)."""
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"KSIM_FAULTS entry {part!r}: expected site=schedule")
            site, _, schedule = part.partition("=")
            self.arm(site.strip(), schedule.strip())

    def _parse(self, site: str, schedule: str) -> _Armed:
        sched, _, err = schedule.partition("@")
        exc = InjectedFault
        if err:
            if err not in ERROR_REGISTRY:
                raise ValueError(
                    f"site {site!r}: unknown error class {err!r} "
                    f"(have {sorted(ERROR_REGISTRY)})"
                )
            exc = ERROR_REGISTRY[err]
        parts = sched.split(":")
        kind = parts[0]
        if kind == "hang" and err:
            # A hang sleeps and CONTINUES — it never raises, so an
            # @error suffix would be silently discarded and the chaos
            # run would exercise something other than what the spec
            # says.  Refuse loudly instead.
            raise ValueError(
                f"site {site!r}: hang schedules never raise; "
                f"drop the @{err} suffix"
            )
        try:
            if kind == "always" and len(parts) == 1:
                return _Armed("always", exc=exc)
            if kind in ("call", "first") and len(parts) == 2:
                n = int(parts[1])
                if n < 1:
                    # Calls are 1-based; call:0/first:0 would arm a site
                    # that can never fire — the vacuously-green chaos
                    # run this parser exists to refuse.
                    raise ValueError(f"{kind}:{n} can never fire (calls are 1-based)")
                return _Armed(kind, n=n, exc=exc)
            if kind == "hang" and len(parts) in (2, 3):
                return _Armed(
                    "hang",
                    hang_s=float(parts[1]),
                    n=int(parts[2]) if len(parts) == 3 else 0,
                )
            if kind == "p" and len(parts) in (2, 3):
                seed = int(parts[2]) if len(parts) == 3 else 0
                return _Armed(
                    "p", prob=float(parts[1]), rng=random.Random(seed), exc=exc
                )
        except ValueError as e:
            raise ValueError(f"site {site!r}: bad schedule {schedule!r}: {e}") from None
        raise ValueError(f"site {site!r}: unknown schedule {schedule!r}")

    # -- the hot path ----------------------------------------------------

    def check(self, site: str) -> None:
        """The injection point.  No-op unless ``site`` is armed; an
        armed site counts the call and, per its schedule, sleeps (hang)
        or raises its error class."""
        # Deliberately unlocked fast path: an unarmed plane must cost one
        # dict truthiness check and nothing else.  The race is benign —
        # dict reads never crash under CPython, a site armed concurrently
        # with a check may miss that one call, which the deterministic
        # schedules never rely on (tests arm before running).
        if not self._sites:  # ksimlint: disable=lock-discipline
            return
        with self._lock:
            entry = self._sites.get(site)
            if entry is None:
                return
            entry.calls += 1
            stats = self._stats.setdefault(site, SiteStats())
            stats.calls += 1
            fire = entry.should_fire()
            if fire:
                stats.fired += 1
                kind, hang_s, exc, calls = (
                    entry.kind, entry.hang_s, entry.exc, entry.calls,
                )
        if not fire:
            return
        # Timeline evidence: a chaos run's question is WHEN the fault
        # landed relative to the phase spans around it, not just that a
        # counter moved.
        if kind == "hang":
            TRACE.event("fault.fired", site=site, mode="hang", seconds=hang_s)
            logger.warning(
                "fault plane: hanging site %s for %.1fs (call %d)",
                site, hang_s, calls,
            )
            time.sleep(hang_s)
            return
        TRACE.event("fault.fired", site=site, mode="raise", exc=exc.__name__)
        logger.warning(
            "fault plane: injecting %s at site %s (call %d)",
            exc.__name__, site, calls,
        )
        # The message is STABLE (no call counter): for ReplayFallback
        # classes it becomes the fallback-histogram bucket, which must
        # not grow a new key per call; the log line above carries the
        # call number for debugging.
        raise exc(f"injected fault at {site}")

    # -- evidence --------------------------------------------------------

    def calls(self, site: str) -> int:
        with self._lock:
            s = self._stats.get(site)
            return s.calls if s else 0

    def fired(self, site: str) -> int:
        """Times ``site`` actually injected (raised or hung) — the
        "fault was exercised" assertion tests lean on."""
        with self._lock:
            s = self._stats.get(site)
            return s.fired if s else 0

    def snapshot(self) -> dict[str, dict[str, int]]:
        """All per-site counters (bench evidence / debugging)."""
        with self._lock:
            return {
                site: {"calls": s.calls, "fired": s.fired}
                for site, s in self._stats.items()
            }


#: The process-global plane every injection site checks.  ``KSIM_FAULTS``
#: arms it at import so subprocess children (bench rungs) inherit fault
#: config through the environment — the stdlib-only bench parent never
#: has to import this module.
FAULTS = FaultPlane()

_env_spec = os.environ.get("KSIM_FAULTS", "")
if _env_spec:
    FAULTS.configure(_env_spec)
