"""Simulator configuration: env vars over config.yaml over defaults.

Mirrors the reference's SimulatorConfiguration v1alpha1 layering
(reference simulator/config/config.go:60-114, config/v1alpha1/types.go:
23-75): every env var overrides the corresponding config.yaml field; the
KubeSchedulerConfiguration loads from ``kubeSchedulerConfigPath``.  Fields
tied to the reference's KWOK topology (etcdURL, kubeApiServerUrl) have no
meaning over the in-memory store; they are accepted and ignored so a
reference config.yaml parses.  The external-cluster handle here is a
snapshot source: ``externalSnapshotPath`` points at a reference-format
snapshot JSON (the analogue of kubeConfig for import/sync).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any

from ksim_tpu.errors import InvalidConfigError
from ksim_tpu.state.resources import JSON

DEFAULT_CONFIG_PATH = "./config.yaml"
DEFAULT_PORT = 1212


@dataclass
class SimulatorConfig:
    host: str = "127.0.0.1"  # bind address; 0.0.0.0 for containers
    port: int = DEFAULT_PORT
    cors_allowed_origin_list: tuple[str, ...] = ()
    kube_scheduler_config_path: str = ""
    external_import_enabled: bool = False
    resource_sync_enabled: bool = False
    external_snapshot_path: str = ""
    kube_config: str = ""  # live-cluster source (reference config.go:88-114)
    resource_import_label_selector: JSON | None = None
    initial_scheduler_cfg: JSON = field(default_factory=dict)


def _env_bool(name: str, fallback: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return fallback
    return v.lower() in ("1", "true", "yes", "on")


def load_config(path: str | None = None) -> SimulatorConfig:
    """config.yaml (if present) + env overrides (reference getPort et al:
    PORT, CORS_ALLOWED_ORIGIN_LIST, KUBE_SCHEDULER_CONFIG_PATH,
    EXTERNAL_IMPORT_ENABLED, RESOURCE_SYNC_ENABLED, EXTERNAL_SNAPSHOT_PATH)."""
    import yaml

    raw: dict[str, Any] = {}
    cfg_path = path or DEFAULT_CONFIG_PATH
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            raw = yaml.safe_load(f) or {}
    elif path:  # explicitly named file must exist
        raise InvalidConfigError(f"config file {path!r} not found")

    port_raw = os.environ.get("PORT")
    if port_raw is None:
        port_raw = raw.get("port")
    try:
        port = DEFAULT_PORT if port_raw in (None, "") else int(port_raw)
    except (TypeError, ValueError):
        raise InvalidConfigError(f"invalid PORT {port_raw!r}") from None
    # Namespaced env var: plain HOST is ambient in csh/CI images.
    host = os.environ.get("KSIM_HOST") or raw.get("host") or "127.0.0.1"
    cors_env = os.environ.get("CORS_ALLOWED_ORIGIN_LIST", "")
    cors = (
        tuple(x for x in cors_env.split(",") if x)
        if cors_env
        else tuple(raw.get("corsAllowedOriginList") or ())
    )
    sched_path = os.environ.get("KUBE_SCHEDULER_CONFIG_PATH") or raw.get(
        "kubeSchedulerConfigPath", ""
    )
    ext_import = _env_bool(
        "EXTERNAL_IMPORT_ENABLED", bool(raw.get("externalImportEnabled"))
    )
    sync = _env_bool("RESOURCE_SYNC_ENABLED", bool(raw.get("resourceSyncEnabled")))
    snap_path = os.environ.get("EXTERNAL_SNAPSHOT_PATH") or raw.get(
        "externalSnapshotPath", ""
    )
    # Explicit sources first (env alias, then yaml).  The reference's
    # KUBECONFIG (docs/environment-variables.md) is honored as a FALLBACK
    # only when an import feature is ON and no source is configured: the
    # ubiquitous kubectl variable must neither leak into unrelated runs
    # nor conflict with an explicitly configured snapshot path.  kubectl
    # allows an os.pathsep-separated list; the first existing entry wins
    # (full kubeconfig merging is out of scope).
    kube_config = os.environ.get("KUBE_CONFIG") or raw.get("kubeConfig", "")
    if (ext_import or sync) and not kube_config and not snap_path:
        ambient = os.environ.get("KUBECONFIG") or ""
        entries = [p for p in ambient.split(os.pathsep) if p]
        existing = [p for p in entries if os.path.exists(p)]
        kube_config = (existing or entries[:1] or [""])[0]
    if ext_import and sync:
        # Reference: mutually exclusive (config.go:88-90).
        raise InvalidConfigError(
            "externalImportEnabled and resourceSyncEnabled cannot be used "
            "simultaneously"
        )
    if (ext_import or sync) and not (snap_path or kube_config):
        raise InvalidConfigError(
            "externalSnapshotPath or kubeConfig must be set when external "
            "import or resource sync is enabled"
        )
    if (ext_import or sync) and snap_path and kube_config:
        raise InvalidConfigError(
            "externalSnapshotPath and kubeConfig are mutually exclusive "
            "import sources"
        )

    sched_cfg: JSON = {}
    if sched_path:
        with open(sched_path) as f:
            sched_cfg = yaml.safe_load(f) or {}

    return SimulatorConfig(
        host=host,
        port=port,
        cors_allowed_origin_list=cors,
        kube_scheduler_config_path=sched_path,
        external_import_enabled=ext_import,
        resource_sync_enabled=sync,
        external_snapshot_path=snap_path,
        kube_config=kube_config,
        resource_import_label_selector=raw.get("resourceImportLabelSelector"),
        initial_scheduler_cfg=sched_cfg,
    )
