"""Scenario replay: timed operation streams driving the simulator.

What the reference designed but never built (reference
keps/140-scenario-based-simulation/README.md — a Scenario CRD whose timed
``operations`` create/update/delete resources while results accumulate in
``.status.result``; the scaffold at scenario/controllers/
scenario_controller.go:28-40 is an empty TODO).  Here it is a library:
an operation stream applied step-by-step to the ClusterStore with a
scheduling pass per step and aggregated results."""

from ksim_tpu.scenario.runner import (
    Operation,
    ScenarioResult,
    ScenarioRunner,
    StepResult,
)
from ksim_tpu.scenario.generate import churn_scenario
from ksim_tpu.scenario.spec import (
    ScenarioSpecError,
    faults_spec_from_doc,
    load_scenario,
    operations_from_spec,
    spec_from_operations,
)
from ksim_tpu.scenario.simulation import run_scheduler_simulation

__all__ = [
    "Operation",
    "ScenarioResult",
    "ScenarioRunner",
    "ScenarioSpecError",
    "StepResult",
    "churn_scenario",
    "faults_spec_from_doc",
    "load_scenario",
    "operations_from_spec",
    "spec_from_operations",
    "run_scheduler_simulation",
]
